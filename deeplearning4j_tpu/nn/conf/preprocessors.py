"""Input preprocessors — shape adapters between layer families.

Parity: ``nn/conf/preprocessor/`` (13 classes, SURVEY.md §2.1). In the
reference each preprocessor implements forward ``preProcess`` and a
manual ``backprop`` transform; here they are pure reshapes traced into
the XLA program, so the backward transform is derived by ``jax.grad`` —
reshapes/transposes are free inside XLA (layout ops, usually fused away).

Conventions: CNN activations are NHWC ([b,h,w,c]; reference NCHW), RNN
activations are [b, t, f] (reference [b, f, t]).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type

import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf.inputs import InputType

_PRE_REGISTRY: Dict[str, Type["InputPreProcessor"]] = {}


def register_preprocessor(cls):
    _PRE_REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d: Dict[str, Any]) -> "InputPreProcessor":
    d = dict(d)
    name = d.pop("@type")
    if name == "ComposableInputPreProcessor":
        kids = tuple(preprocessor_from_dict(c) for c in d["children"])
        return _PRE_REGISTRY[name](children=kids)
    for k, v in d.items():
        if isinstance(v, list):
            d[k] = tuple(v)
    return _PRE_REGISTRY[name](**d)


@dataclasses.dataclass(frozen=True)
class InputPreProcessor:
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError

    def output_type(self, in_type: InputType) -> InputType:
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class CnnToFeedForwardPreProcessor(InputPreProcessor):
    """``CnnToFeedForwardPreProcessor.java`` — [b,h,w,c] -> [b, h*w*c]."""

    def __call__(self, x):
        return x.reshape(x.shape[0], -1)

    def output_type(self, t):
        return InputType.feed_forward(t.flat_size())


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class FeedForwardToCnnPreProcessor(InputPreProcessor):
    """``FeedForwardToCnnPreProcessor.java`` — [b, h*w*c] -> [b,h,w,c]."""

    height: int = 1
    width: int = 1
    channels: int = 1

    def __call__(self, x):
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, t):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class RnnToFeedForwardPreProcessor(InputPreProcessor):
    """``RnnToFeedForwardPreProcessor.java`` — [b,t,f] -> [b*t, f] so dense
    layers apply per-timestep."""

    def __call__(self, x):
        return x.reshape(-1, x.shape[-1])

    def output_type(self, t):
        # carry the sequence length so a later ff->rnn transition can
        # restore [b, t, f] (rnn -> dense -> rnn stacks)
        return InputType(kind="ff", size=t.size, timesteps=t.timesteps)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class FeedForwardToRnnPreProcessor(InputPreProcessor):
    """``FeedForwardToRnnPreProcessor.java`` — [b*t, f] -> [b,t,f]."""

    timesteps: int = 1

    def __call__(self, x):
        return x.reshape(-1, self.timesteps, x.shape[-1])

    def output_type(self, t):
        return InputType.recurrent(t.size, self.timesteps)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class CnnToRnnPreProcessor(InputPreProcessor):
    """``CnnToRnnPreProcessor.java`` — here: [b,h,w,c] -> [b, 1, h*w*c]
    single-step sequence (the reference maps conv output to time-series
    via known time dimension; combined usage goes through reshape)."""

    def __call__(self, x):
        return x.reshape(x.shape[0], 1, -1)

    def output_type(self, t):
        return InputType.recurrent(t.flat_size(), 1)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class RnnToCnnPreProcessor(InputPreProcessor):
    """``RnnToCnnPreProcessor.java`` — [b,t,h*w*c] -> [b*t,h,w,c]."""

    height: int = 1
    width: int = 1
    channels: int = 1

    def __call__(self, x):
        return x.reshape(-1, self.height, self.width, self.channels)

    def output_type(self, t):
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class ReshapePreprocessor(InputPreProcessor):
    """``ReshapePreprocessor.java`` — arbitrary reshape (batch preserved)."""

    shape: Tuple[int, ...] = ()

    def __call__(self, x):
        return x.reshape((x.shape[0],) + tuple(self.shape))

    def output_type(self, t):
        if len(self.shape) == 1:
            return InputType.feed_forward(self.shape[0])
        if len(self.shape) == 3:
            return InputType.convolutional(*self.shape)
        if len(self.shape) == 2:
            return InputType.recurrent(self.shape[1], self.shape[0])
        raise ValueError(self.shape)


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class ZeroMeanPrePreProcessor(InputPreProcessor):
    """``ZeroMeanPrePreProcessor.java`` — subtract per-example mean."""

    def __call__(self, x):
        return x - jnp.mean(x, axis=tuple(range(1, x.ndim)), keepdims=True)

    def output_type(self, t):
        return t


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class UnitVarianceProcessor(InputPreProcessor):
    """``UnitVarianceProcessor.java`` — divide by per-example std."""

    def __call__(self, x):
        std = jnp.std(x, axis=tuple(range(1, x.ndim)), keepdims=True)
        return x / (std + 1e-8)

    def output_type(self, t):
        return t


@register_preprocessor
@dataclasses.dataclass(frozen=True)
class ComposableInputPreProcessor(InputPreProcessor):
    """``ComposableInputPreProcessor.java`` — chain of preprocessors."""

    children: Tuple[InputPreProcessor, ...] = ()

    def __call__(self, x):
        for c in self.children:
            x = c(x)
        return x

    def output_type(self, t):
        for c in self.children:
            t = c.output_type(t)
        return t

    def to_dict(self):
        return {"@type": type(self).__name__,
                "children": [c.to_dict() for c in self.children]}
