"""Graph vertex configurations + functional vertex ops.

Parity: ``nn/conf/graph/*.java`` + ``nn/graph/vertex/impl/*.java`` —
the 9 non-layer DAG ops plus the 2 rnn vertices (SURVEY.md §2.1 "Graph
vertices"). In the reference each vertex has hand-written
doForward/doBackward; here each is a pure function over its input
arrays (backprop via jax.grad), so a vertex config IS its
implementation.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple, Type

import jax.numpy as jnp

_VERTEX_REGISTRY: Dict[str, Type["GraphVertex"]] = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d: Dict[str, Any]) -> "GraphVertex":
    d = dict(d)
    name = d.pop("@type")
    if name == "PreprocessorVertex":
        from deeplearning4j_tpu.nn.conf.preprocessors import preprocessor_from_dict
        return _VERTEX_REGISTRY[name](preprocessor=preprocessor_from_dict(d["preprocessor"]))
    for k, v in d.items():
        if isinstance(v, list):
            d[k] = tuple(v)
    return _VERTEX_REGISTRY[name](**d)


@dataclasses.dataclass(frozen=True)
class GraphVertex:
    """A parameterless DAG op: forward(inputs, masks) -> output."""

    def forward(self, inputs: List[jnp.ndarray],
                masks: Optional[List[Optional[jnp.ndarray]]] = None) -> jnp.ndarray:
        raise NotImplementedError

    def to_dict(self) -> Dict[str, Any]:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            d[f.name] = list(v) if isinstance(v, tuple) else v
        return d


@register_vertex
@dataclasses.dataclass(frozen=True)
class MergeVertex(GraphVertex):
    """``MergeVertex.java`` — concatenate along the feature axis (last
    here; the reference's dim-1 in NCHW/[b,f,t] maps to last in
    NHWC/[b,t,f])."""

    def forward(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)


@register_vertex
@dataclasses.dataclass(frozen=True)
class ElementWiseVertex(GraphVertex):
    """``ElementWiseVertex.java`` — Add / Subtract / Product / Max."""

    op: str = "add"

    def forward(self, inputs, masks=None):
        out = inputs[0]
        for x in inputs[1:]:
            if self.op == "add":
                out = out + x
            elif self.op == "subtract":
                out = out - x
            elif self.op == "product":
                out = out * x
            elif self.op == "max":
                out = jnp.maximum(out, x)
            else:
                raise ValueError(self.op)
        return out


@register_vertex
@dataclasses.dataclass(frozen=True)
class SubsetVertex(GraphVertex):
    """``SubsetVertex.java`` — feature-range slice [from, to] inclusive."""

    from_index: int = 0
    to_index: int = 0

    def forward(self, inputs, masks=None):
        return inputs[0][..., self.from_index:self.to_index + 1]


@register_vertex
@dataclasses.dataclass(frozen=True)
class StackVertex(GraphVertex):
    """``StackVertex.java`` — stack along batch axis (examples appended)."""

    def forward(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)


@register_vertex
@dataclasses.dataclass(frozen=True)
class UnstackVertex(GraphVertex):
    """``UnstackVertex.java`` — take the i-th of ``stack_size`` equal
    batch-axis chunks."""

    from_index: int = 0
    stack_size: int = 1

    def forward(self, inputs, masks=None):
        x = inputs[0]
        step = x.shape[0] // self.stack_size
        return x[self.from_index * step:(self.from_index + 1) * step]


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2NormalizeVertex(GraphVertex):
    """``L2NormalizeVertex.java`` — x / ||x||₂ per example."""

    eps: float = 1e-8

    def forward(self, inputs, masks=None):
        x = inputs[0]
        axes = tuple(range(1, x.ndim))
        n = jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=True) + self.eps)
        return x / n


@register_vertex
@dataclasses.dataclass(frozen=True)
class L2Vertex(GraphVertex):
    """``L2Vertex.java`` — pairwise L2 distance between two inputs → [b, 1]."""

    eps: float = 1e-8

    def forward(self, inputs, masks=None):
        a, b = inputs
        axes = tuple(range(1, a.ndim))
        return jnp.sqrt(jnp.sum((a - b) ** 2, axis=axes) + self.eps)[:, None]


@register_vertex
@dataclasses.dataclass(frozen=True)
class ScaleVertex(GraphVertex):
    """``ScaleVertex.java`` — multiply by a fixed scalar."""

    scale: float = 1.0

    def forward(self, inputs, masks=None):
        return inputs[0] * self.scale


@register_vertex
@dataclasses.dataclass(frozen=True)
class ShiftVertex(GraphVertex):
    """``ShiftVertex.java`` — add a fixed scalar."""

    shift: float = 0.0

    def forward(self, inputs, masks=None):
        return inputs[0] + self.shift


@register_vertex
@dataclasses.dataclass(frozen=True)
class PreprocessorVertex(GraphVertex):
    """``PreprocessorVertex.java`` — wrap an InputPreProcessor as a vertex."""

    preprocessor: Any = None

    def forward(self, inputs, masks=None):
        return self.preprocessor(inputs[0])

    def to_dict(self):
        return {"@type": "PreprocessorVertex", "preprocessor": self.preprocessor.to_dict()}


@register_vertex
@dataclasses.dataclass(frozen=True)
class LastTimeStepVertex(GraphVertex):
    """``rnn/LastTimeStepVertex.java`` — [b,t,f] -> [b,f] at each
    example's last unmasked step (mask of the named input)."""

    mask_input: Optional[str] = None

    def forward(self, inputs, masks=None):
        x = inputs[0]
        mask = masks[0] if masks else None
        if mask is None:
            return x[:, -1, :]
        idx = jnp.maximum(jnp.sum(mask, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0, :]


@register_vertex
@dataclasses.dataclass(frozen=True)
class DuplicateToTimeSeriesVertex(GraphVertex):
    """``rnn/DuplicateToTimeSeriesVertex.java`` — [b,f] -> [b,t,f],
    t taken from a reference input named in config (second input here)."""

    ref_input: Optional[str] = None

    def forward(self, inputs, masks=None):
        x, ref = inputs
        t = ref.shape[1]
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1]))
