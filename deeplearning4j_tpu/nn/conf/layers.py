"""Declarative layer configurations.

Parity: ``nn/conf/layers/*.java`` — 21 Jackson-serializable layer config
types with per-layer overrides of global hyperparameters
(``NeuralNetConfiguration.java:84-86``). Here each config is a frozen
dataclass registered in a polymorphic type registry (the analog of the
reference's Jackson ``registerSubtypes`` :320, including user-defined
custom layers).

All fields with value ``None`` inherit the global default from the
enclosing :class:`~deeplearning4j_tpu.nn.conf.NeuralNetConfiguration`.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple, Type

_LAYER_REGISTRY: Dict[str, Type["Layer"]] = {}


def register_layer(cls: Type["Layer"]) -> Type["Layer"]:
    """Register a layer config type for serialization (the custom-layer
    seam tested by the reference's ``TestCustomLayers.java``)."""
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_from_dict(d: Dict[str, Any]) -> "Layer":
    d = dict(d)
    type_name = d.pop("@type")
    cls = _LAYER_REGISTRY[type_name]
    field_names = {f.name for f in dataclasses.fields(cls)}
    kwargs = {k: v for k, v in d.items() if k in field_names}
    # tuples arrive from JSON as lists
    for f in dataclasses.fields(cls):
        if f.name in kwargs and isinstance(kwargs[f.name], list):
            kwargs[f.name] = tuple(kwargs[f.name])
    if isinstance(kwargs.get("dist"), dict):
        from deeplearning4j_tpu.nn.weights import Distribution
        kwargs["dist"] = Distribution.from_dict(kwargs["dist"])
    return cls(**kwargs)


@dataclasses.dataclass(frozen=True)
class Layer:
    """Base layer config (``nn/conf/layers/Layer.java``)."""

    name: Optional[str] = None
    activation: Optional[str] = None
    weight_init: Optional[str] = None
    bias_init: Optional[float] = None
    # Layers feeding BatchNormalization don't need a bias: BN's beta
    # absorbs it, and on TPU the bias *gradient* is a full HBM reduce
    # over the layer's output — measurably expensive in conv nets.
    has_bias: bool = True
    dist_mean: float = 0.0
    dist_std: float = 1.0
    # explicit WeightInit.DISTRIBUTION source (nn/conf/distribution/):
    # a weights.Distribution; overrides dist_mean/dist_std when set
    dist: Optional[object] = None
    dropout: Optional[float] = None  # keep DL4J semantics: probability of RETAINING is 1-dropout? see layers/base.py
    l1: Optional[float] = None
    l2: Optional[float] = None
    # per-layer updater overrides
    learning_rate: Optional[float] = None
    momentum: Optional[float] = None
    updater: Optional[str] = None
    gradient_normalization: Optional[str] = None
    gradient_normalization_threshold: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        d = {"@type": type(self).__name__}
        for f in dataclasses.fields(self):
            v = getattr(self, f.name)
            if v is not None and v != f.default:
                if dataclasses.is_dataclass(v) and not isinstance(v, type):
                    v = dataclasses.asdict(v)  # e.g. weights.Distribution
                d[f.name] = list(v) if isinstance(v, tuple) else v
        return d


@dataclasses.dataclass(frozen=True)
class FeedForwardLayer(Layer):
    """Base for layers with explicit nIn/nOut
    (``nn/conf/layers/FeedForwardLayer.java``)."""

    n_in: Optional[int] = None  # auto-wired from InputType when None
    n_out: Optional[int] = None


@register_layer
@dataclasses.dataclass(frozen=True)
class DenseLayer(FeedForwardLayer):
    """``nn/conf/layers/DenseLayer.java`` — z = x·W + b, activation."""


@register_layer
@dataclasses.dataclass(frozen=True)
class OutputLayer(FeedForwardLayer):
    """``nn/conf/layers/OutputLayer.java`` — dense + loss function."""

    loss_function: str = "mcxent"


@register_layer
@dataclasses.dataclass(frozen=True)
class RnnOutputLayer(FeedForwardLayer):
    """``nn/conf/layers/RnnOutputLayer.java`` — per-timestep output + loss,
    honoring a [batch, T] label mask."""

    loss_function: str = "mcxent"


@register_layer
@dataclasses.dataclass(frozen=True)
class LossLayer(Layer):
    """``nn/conf/layers/LossLayer.java`` — loss without params (identity
    or activation-only forward)."""

    loss_function: str = "mse"


@register_layer
@dataclasses.dataclass(frozen=True)
class ConvolutionLayer(FeedForwardLayer):
    """``nn/conf/layers/ConvolutionLayer.java``.

    NHWC; kernel [kh, kw, inC, outC]. n_in = input channels. The
    reference's ``cudnnAlgoMode`` knob has no analog — algorithm choice
    belongs to XLA on TPU.
    """

    kernel_size: Tuple[int, int] = (5, 5)
    stride: Tuple[int, int] = (1, 1)
    padding: Tuple[int, int] = (0, 0)
    convolution_mode: str = "truncate"  # truncate|same (reference ConvolutionMode)


class PoolingType:
    MAX = "max"
    AVG = "avg"
    SUM = "sum"
    PNORM = "pnorm"


@register_layer
@dataclasses.dataclass(frozen=True)
class SubsamplingLayer(Layer):
    """``nn/conf/layers/SubsamplingLayer.java`` — max/avg/sum pooling."""

    pooling_type: str = PoolingType.MAX
    kernel_size: Tuple[int, int] = (2, 2)
    stride: Tuple[int, int] = (2, 2)
    padding: Tuple[int, int] = (0, 0)
    pnorm: int = 2


@register_layer
@dataclasses.dataclass(frozen=True)
class BatchNormalization(FeedForwardLayer):
    """``nn/conf/layers/BatchNormalization.java`` — train-time batch stats
    + moving averages for inference, optional learned gamma/beta."""

    decay: float = 0.9
    eps: float = 1e-5
    is_minibatch: bool = True
    lock_gamma_beta: bool = False
    gamma: float = 1.0
    beta: float = 0.0


@register_layer
@dataclasses.dataclass(frozen=True)
class LocalResponseNormalization(Layer):
    """``nn/conf/layers/LocalResponseNormalization.java`` — cross-channel
    LRN (cuDNN slot in the reference; a fused reduce window here)."""

    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesLSTM(FeedForwardLayer):
    """``nn/conf/layers/GravesLSTM.java`` — LSTM with peephole connections
    (Graves 2013 formulation, matching ``LSTMHelpers.java``)."""

    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_layer
@dataclasses.dataclass(frozen=True)
class GravesBidirectionalLSTM(GravesLSTM):
    """``nn/conf/layers/GravesBidirectionalLSTM.java`` — fwd+bwd LSTMs,
    outputs summed (reference semantics)."""


@register_layer
@dataclasses.dataclass(frozen=True)
class AttentionLayer(FeedForwardLayer):
    """Multi-head self-attention over [b, t, f] sequences.

    No reference counterpart (the reference predates attention —
    SURVEY.md §5 long-context note); this is the SURVEY §7.7 extension
    made user-reachable. Backed by ``ops/attention.py``; when a
    sequence-parallel mesh is active (``parallel.mesh.sequence_mesh``),
    the impl automatically switches to the ring-attention kernel
    (``parallel/ring_attention.py``) and shards time over the mesh's
    ``seq`` axis."""

    num_heads: int = 4
    causal: bool = False
    residual: bool = True  # x + attn(x) — standard transformer block wiring


@register_layer
@dataclasses.dataclass(frozen=True)
class EmbeddingLayer(FeedForwardLayer):
    """``nn/conf/layers/EmbeddingLayer.java`` — index lookup as one-hot
    matmul (MXU-friendly gather; input is int indices [batch] or
    [batch, 1])."""


@register_layer
@dataclasses.dataclass(frozen=True)
class SequenceEmbeddingLayer(FeedForwardLayer):
    """Token + learned positional embedding: int indices [b, t] →
    [b, t, n_out]. No reference counterpart (the reference embeds only
    [b] ids, ``EmbeddingLayer.java``); this is the transformer on-ramp
    (SURVEY §7.7 extension)."""

    max_len: int = 2048


@register_layer
@dataclasses.dataclass(frozen=True)
class TransformerBlock(FeedForwardLayer):
    """Pre-LN transformer decoder/encoder block: LN → multi-head
    attention (flash Pallas kernel / ring under a seq mesh) → residual →
    LN → GELU MLP → residual. No reference counterpart (SURVEY §7.7
    extension); n_in == n_out == d_model."""

    num_heads: int = 8
    ffn_mult: int = 4
    causal: bool = True
    # Mixtral-style MoE FFN: > 0 replaces the dense MLP with a top-1
    # routed expert mix (ops/moe.py); shard experts via moe_ep_specs
    num_experts: int = 0
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01


@register_layer
@dataclasses.dataclass(frozen=True)
class MoELayer(FeedForwardLayer):
    """Mixture-of-experts FFN with Switch-style top-1 routing
    (capacity-bounded dense dispatch; see ``ops/moe.py``). No reference
    counterpart (SURVEY §2.6 note 5 — expert parallelism postdates it);
    shard the expert weight dim over a mesh ``expert`` axis for EP.
    Contributes the load-balancing aux loss to the objective via the
    layer-state seam (``__aux_loss__``)."""

    num_experts: int = 8
    ffn_mult: int = 4
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    residual: bool = False


@register_layer
@dataclasses.dataclass(frozen=True)
class AutoEncoder(FeedForwardLayer):
    """``nn/conf/layers/AutoEncoder.java`` — denoising autoencoder for
    layerwise pretraining."""

    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss_function: str = "mse"


class RBMHiddenUnit:
    BINARY = "binary"
    RECTIFIED = "rectified"
    GAUSSIAN = "gaussian"
    SOFTMAX = "softmax"


class RBMVisibleUnit:
    BINARY = "binary"
    GAUSSIAN = "gaussian"
    LINEAR = "linear"
    SOFTMAX = "softmax"


@register_layer
@dataclasses.dataclass(frozen=True)
class RBM(FeedForwardLayer):
    """``nn/conf/layers/RBM.java`` — restricted Boltzmann machine trained
    by contrastive divergence (pretrain path)."""

    hidden_unit: str = RBMHiddenUnit.BINARY
    visible_unit: str = RBMVisibleUnit.BINARY
    k: int = 1  # CD-k steps
    loss_function: str = "reconstruction_crossentropy"


@register_layer
@dataclasses.dataclass(frozen=True)
class ActivationLayer(Layer):
    """``nn/conf/layers/ActivationLayer.java`` — parameterless activation."""


@register_layer
@dataclasses.dataclass(frozen=True)
class DropoutLayer(FeedForwardLayer):
    """``nn/conf/layers/DropoutLayer.java`` — dropout as its own layer."""


@register_layer
@dataclasses.dataclass(frozen=True)
class GlobalPoolingLayer(Layer):
    """Global pooling over time (RNN) or space (CNN). Extension the
    reference gained in 0.7; needed for masked sequence classification."""

    pooling_type: str = PoolingType.MAX
