"""Post-training quantization for serving: int8/fp8 weights with
on-the-fly dequant, and the accuracy-delta gate that ships with them.

The serving path is bandwidth-bound (``gemm_bf16`` runs at 0.83 MFU
while decode-side attention sits ~0.25): every generated token moves
the whole weight set and the whole KV cache through HBM, so halving or
quartering the *bytes* is worth more than any FLOP trick. This module
is the weights half of that arc (nn/kvpool.py carries the KV half):

- :func:`quantize` — the LLM.int8()/AWQ per-output-channel recipe as a
  pure post-training pass: ``quantize(net, dtype="int8")`` returns a
  NEW net (same conf, same layer names) whose Dense / Embedding /
  TransformerBlock projection matrices are stored as int8 (or
  fp8-e4m3) alongside float32 per-output-channel scales under
  ``<name>_qscale`` keys. Biases, LayerNorm affines and positional
  tables stay float32 — they are tiny and precision-critical.
- :func:`qmatmul` / :func:`qtake` — the dequant *fused into the op*:
  ``(x @ w_int8) * scale`` (the per-output-channel scale commutes with
  the contraction, so compute stays bf16/f32 while HBM moves int8
  bytes) and ``take(w_int8, ids) * scale`` for embedding gathers. The
  layer impls call these unconditionally; an unquantized weight falls
  straight through to the original matmul/gather, so every existing
  program — forward, prefill, prefill_paged, decode_step, the whole
  compiled ladder — is byte-identical when nothing is quantized.
- :func:`kv_quantize` / :func:`kv_dequantize` — the paged-pool
  quantization primitive: per-(position, head) scales (amax over
  head_dim). Per-token granularity is deliberate: a block written
  incrementally by decode steps and the same block re-written by a
  resume's prefill scatter quantize IDENTICALLY, which is what keeps
  the preempt/resume and prefix-cache bitwise-replay contracts alive
  on a quantized pool (a per-block running scale would re-quantize
  history and diverge).
- :func:`accuracy_gate` — the quality bound the perf claim ships
  with: teacher-forced greedy token match rate + logit MSE +
  next-token cross-entropy delta vs the fp32 net on a fixed seeded
  workload, with pass/fail thresholds. ``make_quality_gate`` adapts it
  to the ``ModelRegistry.deploy(quality_gate=...)`` seam so a
  quantized canary is arbitrated by measured quality, and
  ``bench.py quantized_serving`` reports the same numbers.

Numeric contract (MIGRATION.md "Quantized serving"): the quantized
lane is EXACT versus itself — greedy tokens are bitwise-reproducible
across runs and invariant to coalescing/preemption/cotenants, the
house determinism bar — but only bounded-delta versus fp32 (the gate's
thresholds are the bound). Quantized nets are serving-only: the round()
in the weights has no useful gradient, so ``fit`` refuses them.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.monitor import (
    QUANT_GATE_OUTCOME_COUNTER,
    QUANT_MODELS_GAUGE,
    QUANT_SCALE_ABSMAX_GAUGE,
    get_registry,
)

#: params-dict suffix marking a weight as quantized: ``params["W"]`` is
#: the int8/fp8 array and ``params["W" + QSCALE]`` its float32
#: per-output-channel scale vector.
QSCALE = "_qscale"

#: supported storage modes -> (jnp storage dtype, quantization max).
#: int8 is symmetric round-to-nearest at +-127; fp8 uses the e4m3 grid
#: (max normal 448) — "fp8-emulated" on backends without native fp8
#: matmul: storage/HBM is 1 byte/weight, compute upcasts on the fly.
_MODES: Dict[str, Tuple[Any, float]] = {
    "int8": (jnp.int8, 127.0),
    "fp8": (jnp.float8_e4m3fn, 448.0),
}

_quantized_nets: Dict[str, int] = {}


def quant_modes() -> Tuple[str, ...]:
    return tuple(sorted(_MODES))


def is_quantized(params: Dict[str, Any], name: str) -> bool:
    return (name + QSCALE) in params


def qmatmul(x, params: Dict[str, Any], name: str):
    """``x @ params[name]`` with on-the-fly dequant when the weight is
    quantized: the int8/fp8 matrix upcasts to ``x.dtype`` inside the
    program (HBM reads stay 1 byte/weight) and the per-output-channel
    scale lands as a fused post-multiply — ``(x @ q) * s`` equals
    ``x @ (q * s)`` exactly because the scale is constant along the
    contraction. Unquantized weights take the original path (matching
    the ``W.astype(x.dtype)`` idiom of every call site) bit for bit."""
    w = params[name]
    sc = params.get(name + QSCALE)
    y = x @ w.astype(x.dtype)
    if sc is None:
        return y
    return y * sc.astype(y.dtype)


def qtake(params: Dict[str, Any], name: str, idx, out_dtype=None):
    """Embedding gather with on-the-fly dequant: rows gather in storage
    precision (1 byte/row-element when quantized), then scale
    per-output-channel. ``out_dtype`` pins the result dtype for the
    quantized path (defaults to the scale's dtype); unquantized weights
    gather exactly as before."""
    w = params[name]
    z = jnp.take(w, idx, axis=0)
    sc = params.get(name + QSCALE)
    if sc is None:
        return z
    dt = out_dtype if out_dtype is not None else sc.dtype
    return z.astype(dt) * sc.astype(dt)


def quantize_array(w, mode: str = "int8"):
    """Per-output-channel quantization of one ``[in, out]`` matrix (or
    ``[vocab, d]`` embedding): scale[j] = amax(|w[:, j]|) / qmax, the
    LLM.int8() vector-wise recipe. Returns (q, scale_f32)."""
    if mode not in _MODES:
        raise ValueError(f"unknown quantization dtype {mode!r}; pick "
                         f"from {quant_modes()}")
    storage, qmax = _MODES[mode]
    wf = jnp.asarray(w, jnp.float32)
    if wf.ndim != 2:
        raise ValueError(f"per-channel quantization needs a 2-D matrix, "
                         f"got shape {wf.shape}")
    sc = jnp.maximum(jnp.max(jnp.abs(wf), axis=0) / qmax, 1e-12)
    if storage == jnp.int8:
        q = jnp.clip(jnp.round(wf / sc), -qmax, qmax).astype(jnp.int8)
    else:
        q = (wf / sc).astype(storage)
    return q, sc.astype(jnp.float32)


def dequantize_array(q, sc):
    """The reference inverse of :func:`quantize_array` (test oracle)."""
    return q.astype(jnp.float32) * sc.astype(jnp.float32)


# ----------------------------------------------------- KV-pool primitive


def kv_qparams(mode: str) -> Tuple[Any, float]:
    """(storage dtype, qmax) for a quantized KV pool mode."""
    if mode not in _MODES:
        raise ValueError(
            f"unknown KV quantization mode {mode!r}; pick from "
            f"{quant_modes()}")
    return _MODES[mode]


def kv_qmax(storage_dtype) -> float:
    """Quantization max for a KV storage dtype (static at trace time —
    the pool arrays' dtype IS the mode, no extra pytree leaf needed)."""
    dt = jnp.dtype(storage_dtype)
    if dt == jnp.dtype(jnp.int8):
        return 127.0
    if dt == jnp.dtype(jnp.float8_e4m3fn):
        return 448.0
    raise ValueError(f"not a quantized KV storage dtype: {dt}")


def kv_quantize(x, storage_dtype, qmax: Optional[float] = None):
    """Quantize K/V values with a per-(…, head) scale over the trailing
    head_dim axis: ``x`` is ``[..., h, hd]``, the scale is ``[..., h]``
    float32. Traced-code only (runs inside scatter/burst programs).
    Per-token scales make quantization a pure elementwise function of
    the written values — a resume's prefill re-quantizes bit-identically
    to the original incremental decode writes, the property every
    replay/preemption contract on the pool depends on. The scale floor
    keeps unwritten/zero positions exactly zero after dequant."""
    if qmax is None:
        qmax = kv_qmax(storage_dtype)
    xf = x.astype(jnp.float32)
    sc = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1) / qmax, 1e-12)
    scaled = xf / sc[..., None]
    if storage_dtype == jnp.int8:
        q = jnp.clip(jnp.round(scaled), -qmax, qmax).astype(jnp.int8)
    else:
        q = scaled.astype(storage_dtype)
    return q, sc.astype(jnp.float32)


def kv_dequantize(q, sc, dtype):
    """Dequantize gathered K/V: ``q`` ``[..., h, hd]`` storage ints/fp8,
    ``sc`` ``[..., h]`` — back to the compute dtype for attention."""
    return q.astype(dtype) * sc[..., None].astype(dtype)


# ------------------------------------------------------- the net pass

#: which param names quantize per impl family. TransformerBlock MoE
#: expert tensors (3-D) and LSTM recurrences are out of scope — the
#: serving-transformer projections are where the bytes are.
_DENSE_NAMES = ("W",)
_TRANSFORMER_NAMES = ("Wqkv", "Wo", "W1", "W2")
_EMBED_NAMES = ("W",)


def _quant_targets(impl) -> Tuple[str, ...]:
    from deeplearning4j_tpu.nn.layers.feedforward import (BaseDenseImpl,
                                                          EmbeddingImpl)
    from deeplearning4j_tpu.nn.layers.transformer import (
        SequenceEmbeddingImpl, TransformerBlockImpl)
    if isinstance(impl, TransformerBlockImpl):
        return _TRANSFORMER_NAMES
    if isinstance(impl, (SequenceEmbeddingImpl, EmbeddingImpl)):
        return _EMBED_NAMES
    if isinstance(impl, BaseDenseImpl):
        return _DENSE_NAMES
    return ()


def _iter_impls(net) -> List[Any]:
    impls = net.impls
    if isinstance(impls, dict):
        return list(impls.values())
    return list(impls)


def quantized_param_bytes(params: Dict[str, Dict[str, Any]]) -> int:
    """Actual byte footprint of a params pytree (what the registry's
    pinned-bytes accounting charges a quantized version)."""
    total = 0
    for leaf in jax.tree.leaves(params):
        total += int(np.asarray(leaf).nbytes if not hasattr(leaf, "nbytes")
                     else leaf.nbytes)
    return total


def quantize(net, dtype: str = "int8"):
    """Post-training quantization pass: returns a NEW net with the same
    configuration and layer names whose Dense/Embedding/TransformerBlock
    projection weights are stored in ``dtype`` (``"int8"`` or
    ``"fp8"``) with float32 per-output-channel scales; every other
    parameter (biases, LayerNorms, positions, recurrences, MoE experts)
    stays float32. The result is a normal net — it serves through every
    existing engine/scheduler/registry path and deploys as a
    ``ModelRegistry`` version — but it is inference-only
    (``net.quantized`` is set and ``fit`` refuses it)."""
    if dtype not in _MODES:
        raise ValueError(f"unknown quantization dtype {dtype!r}; pick "
                         f"from {quant_modes()}")
    if net.params is None:
        raise ValueError("quantize() needs an initialized net (params)")
    if getattr(net, "quantized", None) is not None:
        raise ValueError(
            f"net is already quantized ({net.quantized}); re-quantizing "
            "quantized weights compounds the error — quantize the fp32 "
            "original")
    clone = type(net)(net.conf)
    clone.init(dtype=net._dtype)
    reg = get_registry()
    new_params: Dict[str, Dict[str, Any]] = {}
    by_name = {impl.name: impl for impl in _iter_impls(clone)}
    for lname, p in net.params.items():
        impl = by_name.get(lname)
        targets = _quant_targets(impl) if impl is not None else ()
        q: Dict[str, Any] = {}
        for pname, v in p.items():
            if pname in targets and getattr(v, "ndim", 0) == 2:
                qv, sc = quantize_array(v, dtype)
                q[pname] = qv
                q[pname + QSCALE] = sc
                reg.gauge(
                    QUANT_SCALE_ABSMAX_GAUGE,
                    "Largest per-output-channel dequant scale per "
                    "quantized weight matrix",
                    layer=lname, param=pname).set(
                        float(jnp.max(sc)))
            else:
                q[pname] = v
        new_params[lname] = q
    clone.params = new_params
    clone.states = jax.tree.map(lambda v: v, net.states) \
        if net.states is not None else None
    clone.quantized = dtype
    _quantized_nets[dtype] = _quantized_nets.get(dtype, 0) + 1
    reg.gauge(QUANT_MODELS_GAUGE,
              "Quantized nets produced by quantize() in this process",
              dtype=dtype).set(float(_quantized_nets[dtype]))
    return clone


# -------------------------------------------------- accuracy-delta gate


def _sequence_logits(net, ids: np.ndarray) -> np.ndarray:
    """Teacher-forced per-position next-token logits [b, t, V] (f32)
    from ONE causal forward — the workhorse of the gate: both nets see
    identical contexts at every position, so one token flip never
    compounds into a diverged rollout."""
    from deeplearning4j_tpu.nn.generate import (TransformerGenerator,
                                                build_generator)
    from deeplearning4j_tpu.util.dtypes import cast_floats

    gen = build_generator(net)
    if not isinstance(gen, TransformerGenerator):
        raise ValueError("accuracy_gate scores transformer stacks; got "
                         f"{type(gen).__name__}")
    cd = net._cd
    cast = (lambda p: cast_floats(p, cd)) if cd is not None else (lambda p: p)

    key = ("quant_gate_logits", ids.shape[1])
    fn = net._jits.get(key)
    if fn is None:
        def logits_fn(params, ids_d):
            x, _ = gen.emb.forward(cast(params[gen.emb.name]), ids_d,
                                   {}, False)
            for blk in gen.blocks:
                x, _ = blk.forward(cast(params[blk.name]), x,
                                   blk.init_state(), False)
            p = cast(params[gen.head.name])
            if hasattr(gen.head, "preout"):
                return gen.head.preout(p, x).astype(jnp.float32)
            return x.astype(jnp.float32)
        fn = net._jits[key] = jax.jit(logits_fn)
    return np.asarray(fn(net.params, jnp.asarray(ids, jnp.int32)))


def _xent(logits: np.ndarray, targets: np.ndarray) -> float:
    """Mean next-token cross-entropy of [b, t, V] logits against the
    [b, t] shifted targets (positions 0..t-2 predict 1..t-1)."""
    lg = logits[:, :-1].astype(np.float64)
    tg = targets[:, 1:]
    m = lg.max(axis=-1, keepdims=True)
    lse = m[..., 0] + np.log(np.exp(lg - m).sum(axis=-1))
    picked = np.take_along_axis(lg, tg[..., None], axis=-1)[..., 0]
    return float(np.mean(lse - picked))


def gate_workload(vocab: int, rows: int = 8, length: int = 24,
                  seed: int = 0) -> np.ndarray:
    """The FIXED seeded token workload both the canary gate and
    ``bench.py quantized_serving`` score on: same seed ⇒ same ids ⇒
    the gate verdict is a pure function of the two nets."""
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab, (rows, length)).astype(np.int32)


def accuracy_gate(ref_net, cand_net, ids: Optional[np.ndarray] = None, *,
                  rows: int = 8, length: int = 24, seed: int = 0,
                  min_greedy_match: float = 0.995,
                  max_eval_delta: float = 0.005,
                  max_logit_mse: Optional[float] = None
                  ) -> Dict[str, Any]:
    """Accuracy-delta harness: score ``cand_net`` against ``ref_net``
    on a fixed seeded workload (or explicit ``ids`` [b, t]) and apply
    the thresholds. Returns::

        {"passed": bool, "greedy_match_rate": …, "logit_mse": …,
         "eval_metric": …, "eval_metric_ref": …, "eval_metric_delta": …,
         "positions": n, "thresholds": {...}}

    - **greedy_match_rate** — fraction of teacher-forced positions
      where both nets' argmax token agrees (the serving-visible
      metric: greedy decode flips exactly where this flips);
    - **logit_mse** — mean squared logit delta (drift magnitude even
      where the argmax survives);
    - **eval_metric_delta** — relative next-token cross-entropy change
      (the "eval metric" of a language model workload).

    The outcome ticks ``dl4j_quant_accuracy_gate_outcome_total``."""
    if ids is None:
        vocab = int(_iter_impls(ref_net)[0].conf.n_in)
        ids = gate_workload(vocab, rows=rows, length=length, seed=seed)
    ids = np.asarray(ids, np.int32)
    lr = _sequence_logits(ref_net, ids)
    lq = _sequence_logits(cand_net, ids)
    match = float(np.mean(np.argmax(lr, -1) == np.argmax(lq, -1)))
    mse = float(np.mean((lr - lq) ** 2))
    xr = _xent(lr, ids)
    xq = _xent(lq, ids)
    delta = abs(xq - xr) / max(abs(xr), 1e-9)
    passed = match >= min_greedy_match and delta <= max_eval_delta
    if max_logit_mse is not None:
        passed = passed and mse <= max_logit_mse
    get_registry().counter(
        QUANT_GATE_OUTCOME_COUNTER,
        "Quantization accuracy-gate verdicts by outcome",
        outcome="pass" if passed else "fail").inc()
    return {
        "passed": bool(passed),
        "greedy_match_rate": round(match, 6),
        "logit_mse": mse,
        "eval_metric": round(xq, 6),
        "eval_metric_ref": round(xr, 6),
        "eval_metric_delta": round(delta, 6),
        "positions": int(lr.shape[0] * lr.shape[1]),
        "thresholds": {"min_greedy_match": min_greedy_match,
                       "max_eval_delta": max_eval_delta,
                       "max_logit_mse": max_logit_mse},
    }


def make_quality_gate(ids: Optional[np.ndarray] = None, *,
                      rows: int = 8, length: int = 24, seed: int = 0,
                      min_greedy_match: float = 0.995,
                      max_eval_delta: float = 0.005,
                      max_logit_mse: Optional[float] = None):
    """Adapter for ``ModelRegistry.deploy(quality_gate=...)``: the
    returned callable takes (stable_net_or_None, candidate_net) and
    returns the :func:`accuracy_gate` verdict dict (a candidate with no
    stable to compare against passes trivially — there is no reference
    to be bounded against)."""
    def gate(stable_net, cand_net) -> Dict[str, Any]:
        if stable_net is None:
            return {"passed": True, "greedy_match_rate": 1.0,
                    "logit_mse": 0.0, "eval_metric_delta": 0.0,
                    "skipped": "no stable version to compare against"}
        return accuracy_gate(
            stable_net, cand_net, ids, rows=rows, length=length,
            seed=seed, min_greedy_match=min_greedy_match,
            max_eval_delta=max_eval_delta, max_logit_mse=max_logit_mse)
    return gate
