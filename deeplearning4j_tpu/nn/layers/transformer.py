"""Transformer block + sequence embedding layer impls.

No reference counterpart (SURVEY §7.7 extension — the reference's only
sequence model is the Graves LSTM); these are the layers the modern
long-context stack is built from, wired so ONE config runs single-chip
(flash Pallas kernel, ``ops/flash_attention.py``) or sequence-parallel
(ring attention over the mesh ``seq`` axis, DP×SP composed) with no
model change — the same auto-select doctrine as ``AttentionImpl``.

Pre-LN wiring (x + Attn(LN(x)), x + MLP(LN(x))): the standard stable
variant; LayerNorm runs in f32 even under a bf16 compute policy
(variance of bf16 activations underflows), matching the output-head-f32
rule in ``multilayer.py``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.attention import dispatch_attention
from deeplearning4j_tpu.nn.layers.base import (
    LayerImpl, apply_dropout, register_impl)
from deeplearning4j_tpu.nn.layers.moe import (
    AUX_LOSS_KEY, init_moe_params, run_moe_ffn)
from deeplearning4j_tpu.nn.weights import init_weights


def _layer_norm(x, gamma, beta, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return out.astype(x.dtype)


@register_impl(L.SequenceEmbeddingLayer)
class SequenceEmbeddingImpl(LayerImpl):
    """int ids [b, t] → [b, t, d]: token gather + learned positions."""

    cast_input = False  # ids must stay exact (see LayerImpl.cast_input)

    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        kw, kp = jax.random.split(key)
        W = init_weights(kw, (c.n_in, c.n_out), self.weight_init,
                         c.n_in, c.n_out, c.dist_mean, c.dist_std)
        P = 0.01 * jax.random.normal(kp, (c.max_len, c.n_out), jnp.float32)
        return {"W": W, "P": P}

    def forward(self, params, x, state, train, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:  # one-hot input tolerated
            idx = jnp.argmax(idx, axis=-1)
        t = idx.shape[1]
        if t > self.conf.max_len:
            raise ValueError(f"sequence length {t} > max_len {self.conf.max_len}")
        z = jnp.take(params["W"], idx, axis=0) + params["P"][:t][None]
        return z, state


@register_impl(L.TransformerBlock)
class TransformerBlockImpl(LayerImpl):
    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        if c.n_out != c.n_in:
            raise ValueError("TransformerBlock needs n_in == n_out (d_model)")
        if c.n_out % c.num_heads != 0:
            raise ValueError(f"d_model {c.n_out} not divisible by "
                             f"num_heads {c.num_heads}")
        d, f = c.n_out, c.ffn_mult * c.n_out
        # split(key, 4) as in the dense-only original: a fixed seed must
        # keep producing bit-identical dense-block inits
        ks = jax.random.split(key, 4)
        mk = lambda k, shape: init_weights(k, shape, self.weight_init,
                                           shape[0], shape[1],
                                           c.dist_mean, c.dist_std)
        params = {
            "Wqkv": mk(ks[0], (d, 3 * d)),
            "Wo": mk(ks[1], (d, d)),
            "ln1_g": jnp.ones((d,), jnp.float32),
            "ln1_b": jnp.zeros((d,), jnp.float32),
            "ln2_g": jnp.ones((d,), jnp.float32),
            "ln2_b": jnp.zeros((d,), jnp.float32),
        }
        if c.num_experts > 0:  # Mixtral-style routed MLP (shared init)
            params.update(init_moe_params(
                ks[2], d, f, c.num_experts, self.weight_init,
                c.dist_mean, c.dist_std))
        else:
            params.update({
                "W1": mk(ks[2], (d, f)), "b1": jnp.zeros((f,), jnp.float32),
                "W2": mk(ks[3], (f, d)), "b2": jnp.zeros((d,), jnp.float32),
            })
        return params

    def init_state(self):
        if self.conf.num_experts > 0:
            return {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}
        return {}

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        if x.ndim != 3:
            raise ValueError(f"TransformerBlock needs [b, t, d], got {x.shape}")
        b, t, d = x.shape
        h_count, hd = c.num_heads, c.n_out // c.num_heads
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        qkv = h @ params["Wqkv"].astype(h.dtype)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda z: z.reshape(b, t, h_count, hd)
        q, k, v = shape(q), shape(k), shape(v)
        o = dispatch_attention(q, k, v, causal=c.causal, mask=mask)
        attn = o.reshape(b, t, d) @ params["Wo"].astype(x.dtype)
        if train and self.dropout_rate > 0.0 and rng is not None:
            attn = apply_dropout(attn, self.dropout_rate,
                                 jax.random.fold_in(rng, 1))
        x = x + attn

        h2 = _layer_norm(x, params["ln2_g"], params["ln2_b"])
        new_state = state
        if c.num_experts > 0:  # routed expert MLP (Mixtral wiring)
            mlp2, new_state = run_moe_ffn(
                params, h2.reshape(-1, d), c.capacity_factor,
                c.aux_loss_weight, mask=mask)
            mlp = mlp2.reshape(b, t, d)
        else:
            mlp = jax.nn.gelu(h2 @ params["W1"].astype(x.dtype)
                              + params["b1"].astype(x.dtype))
            mlp = mlp @ params["W2"].astype(x.dtype) + params["b2"].astype(x.dtype)
        if train and self.dropout_rate > 0.0 and rng is not None:
            mlp = apply_dropout(mlp, self.dropout_rate,
                                jax.random.fold_in(rng, 2))
        out = x + mlp
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, new_state
