"""Transformer block + sequence embedding layer impls.

No reference counterpart (SURVEY §7.7 extension — the reference's only
sequence model is the Graves LSTM); these are the layers the modern
long-context stack is built from, wired so ONE config runs single-chip
(flash Pallas kernel, ``ops/flash_attention.py``) or sequence-parallel
(ring attention over the mesh ``seq`` axis, DP×SP composed) with no
model change — the same auto-select doctrine as ``AttentionImpl``.

Pre-LN wiring (x + Attn(LN(x)), x + MLP(LN(x))): the standard stable
variant; LayerNorm runs in f32 even under a bf16 compute policy
(variance of bf16 activations underflows), matching the output-head-f32
rule in ``multilayer.py``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.attention import (dispatch_attention,
                                                    xla_attention)
from deeplearning4j_tpu.nn.layers.base import (
    LayerImpl, apply_dropout, register_impl)
from deeplearning4j_tpu.nn.layers.moe import (
    AUX_LOSS_KEY, init_moe_params, run_moe_ffn)
from deeplearning4j_tpu.nn.quantize import (kv_dequantize, kv_quantize,
                                            qmatmul, qtake)
from deeplearning4j_tpu.nn.weights import init_weights


def _layer_norm(x, gamma, beta, eps=1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + eps) * gamma + beta
    return out.astype(x.dtype)


@register_impl(L.SequenceEmbeddingLayer)
class SequenceEmbeddingImpl(LayerImpl):
    """int ids [b, t] → [b, t, d]: token gather + learned positions."""

    cast_input = False  # ids must stay exact (see LayerImpl.cast_input)

    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        kw, kp = jax.random.split(key)
        W = init_weights(kw, (c.n_in, c.n_out), self.weight_init,
                         c.n_in, c.n_out, c.dist_mean, c.dist_std,
                         dist=c.dist)
        P = 0.01 * jax.random.normal(kp, (c.max_len, c.n_out), jnp.float32)
        return {"W": W, "P": P}

    def forward(self, params, x, state, train, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 3:  # one-hot input tolerated
            idx = jnp.argmax(idx, axis=-1)
        t = idx.shape[1]
        if t > self.conf.max_len:
            raise ValueError(f"sequence length {t} > max_len {self.conf.max_len}")
        z = qtake(params, "W", idx) + params["P"][:t][None]
        return self._slice_replicate(z), state


@register_impl(L.TransformerBlock)
class TransformerBlockImpl(LayerImpl):
    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        if c.n_out != c.n_in:
            raise ValueError("TransformerBlock needs n_in == n_out (d_model)")
        if c.n_out % c.num_heads != 0:
            raise ValueError(f"d_model {c.n_out} not divisible by "
                             f"num_heads {c.num_heads}")
        d, f = c.n_out, c.ffn_mult * c.n_out
        # split(key, 4) as in the dense-only original: a fixed seed must
        # keep producing bit-identical dense-block inits
        ks = jax.random.split(key, 4)
        mk = lambda k, shape: init_weights(k, shape, self.weight_init,
                                           shape[0], shape[1],
                                           c.dist_mean, c.dist_std,
                                           dist=c.dist)
        params = {
            "Wqkv": mk(ks[0], (d, 3 * d)),
            "Wo": mk(ks[1], (d, d)),
            "ln1_g": jnp.ones((d,), jnp.float32),
            "ln1_b": jnp.zeros((d,), jnp.float32),
            "ln2_g": jnp.ones((d,), jnp.float32),
            "ln2_b": jnp.zeros((d,), jnp.float32),
        }
        if c.num_experts > 0:  # Mixtral-style routed MLP (shared init)
            params.update(init_moe_params(
                ks[2], d, f, c.num_experts, self.weight_init,
                c.dist_mean, c.dist_std, dist=c.dist))
        else:
            params.update({
                "W1": mk(ks[2], (d, f)), "b1": jnp.zeros((f,), jnp.float32),
                "W2": mk(ks[3], (f, d)), "b2": jnp.zeros((d,), jnp.float32),
            })
        return params

    def init_state(self):
        if self.conf.num_experts > 0:
            return {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}
        return {}

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        if x.ndim != 3:
            raise ValueError(f"TransformerBlock needs [b, t, d], got {x.shape}")
        b, t, d = x.shape
        h_count, hd = c.num_heads, c.n_out // c.num_heads
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        qkv = qmatmul(h, params, "Wqkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda z: z.reshape(b, t, h_count, hd)
        q, k, v = shape(q), shape(k), shape(v)
        if self._slice_mesh is not None:
            # sliced serving: heads are sharded over tp — the Pallas
            # flash kernel cannot see the mesh, so stay on the XLA
            # formulation GSPMD partitions per-head
            with xla_attention():
                o = dispatch_attention(q, k, v, causal=c.causal, mask=mask)
        else:
            o = dispatch_attention(q, k, v, causal=c.causal, mask=mask)
        attn = qmatmul(self._slice_replicate(o.reshape(b, t, d)),
                       params, "Wo")
        if train and self.dropout_rate > 0.0 and rng is not None:
            attn = apply_dropout(attn, self.dropout_rate,
                                 jax.random.fold_in(rng, 1))
        # replicate BEFORE ln2: its mean/var reduce over the feature dim
        # the attn matmul left sharded
        x = self._slice_replicate(x + attn)

        h2 = _layer_norm(x, params["ln2_g"], params["ln2_b"])
        mlp, new_state = self._ffn(params, h2.reshape(-1, d), state,
                                   mask=mask,
                                   capacity_factor=c.capacity_factor)
        mlp = mlp.reshape(b, t, d)
        if train and self.dropout_rate > 0.0 and rng is not None:
            mlp = apply_dropout(mlp, self.dropout_rate,
                                jax.random.fold_in(rng, 2))
        out = self._slice_replicate(x + mlp)
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, new_state

    def _ffn(self, params, h2, state, mask=None, capacity_factor=None):
        """Post-LN2 feed-forward over flattened tokens [n, d]: dense
        GELU MLP or routed experts — the ONE implementation both
        ``forward`` and ``decode_step`` use."""
        c = self.conf
        if c.num_experts > 0:
            return run_moe_ffn(params, h2, capacity_factor,
                               c.aux_loss_weight, mask=mask)
        mlp = jax.nn.gelu(qmatmul(h2, params, "W1")
                          + params["b1"].astype(h2.dtype))
        # sliced: W1 is column-sharded so mlp is sharded on its hidden
        # dim — all-gather it before W2 contracts over that dim, so the
        # contraction never reduces across shards (bitwise seam)
        mlp = self._slice_replicate(mlp)
        mlp = qmatmul(mlp, params, "W2") \
            + params["b2"].astype(h2.dtype)
        return mlp, state

    # ------------------------------------------- incremental decoding

    def init_cache(self, batch: int, max_len: int, dtype=jnp.float32):
        """KV cache for autoregressive decoding (the transformer analog
        of ``BaseRecurrentLayer`` stateMap / ``rnnTimeStep``)."""
        c = self.conf
        h, hd = c.num_heads, c.n_out // c.num_heads
        shape = (batch, max_len, h, hd)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    def prefill(self, params, x, cache):
        """Batched prompt forward that ALSO writes every position's K/V
        into ``cache`` (the ``decode_step`` layout): [b, t, d] →
        ([b, t, d], cache). The attention/residual math is exactly
        ``forward``'s (causal flash/ring dispatch, maskless), so prefill
        hidden states equal ``forward``'s; the FFN routes NO-DROP like
        ``decode_step`` when MoE (serving never wants dropped tokens).
        Right-padded prompt rows are safe: a padded position's garbage
        K/V slot is only ever attended to after a decode step has
        overwritten it (decode writes slot ``pos`` before reading)."""
        c = self.conf
        b, t, d = x.shape
        h_count, hd = c.num_heads, c.n_out // c.num_heads
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        qkv = qmatmul(h, params, "Wqkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda z: z.reshape(b, t, h_count, hd)
        q, k, v = shape(q), shape(k), shape(v)
        ck = jax.lax.dynamic_update_slice_in_dim(
            cache["k"], k.astype(cache["k"].dtype), 0, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(
            cache["v"], v.astype(cache["v"].dtype), 0, axis=1)
        if self._slice_mesh is not None:
            with xla_attention():
                o = dispatch_attention(q, k, v, causal=c.causal, mask=None)
        else:
            o = dispatch_attention(q, k, v, causal=c.causal, mask=None)
        x = self._slice_replicate(
            x + qmatmul(self._slice_replicate(o.reshape(b, t, d)),
                        params, "Wo"))
        h2 = _layer_norm(x, params["ln2_g"], params["ln2_b"])
        mlp, _ = self._ffn(params, h2.reshape(-1, d), {},
                           capacity_factor=float(max(1, c.num_experts)))
        return self._slice_replicate(x + mlp.reshape(b, t, d)), \
            {"k": ck, "v": cv}

    def prefill_paged(self, params, x, pool, table, pos, write_ok):
        """Chunked (tail) prefill straight through the paged pool — the
        prefix-cache admission path: the prompt's cached prefix already
        lives in pool blocks, so only the TAIL runs here. ``x`` is
        [b, t, d] tail activations, ``pos`` [b, t] each tail token's
        ABSOLUTE cache position (per-row ``start + j`` — the cached
        prefix length enters traced, so one compiled program serves any
        match-length mix), ``write_ok`` [b, t] masks padding positions
        (their writes redirect to trash block 0, the ``decode_step``
        discipline). Tail K/V scatters into the row's table blocks
        FIRST, then attention gathers the whole table back — so tail
        self-attention sees its own fresh K/V and the cached prefix in
        one causal pass. Gathered positions past each query's ``pos``
        (stale partial-block content, trash padding) are causally
        masked, numerically inert exactly like the dense path's padded
        tail. Returns ([b, t, d] out, new pool {"k", "v"})."""
        c = self.conf
        b, t, d = x.shape
        h_count, hd = c.num_heads, c.n_out // c.num_heads
        h = _layer_norm(x, params["ln1_g"], params["ln1_b"])
        qkv = qmatmul(h, params, "Wqkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda z: z.reshape(b, t, h_count, hd)
        q, k, v = shape(q), shape(k), shape(v)
        kp, vp = pool["k"], pool["v"]        # [NB, bs, h, hd] shared pool
        bs = kp.shape[1]
        mb = table.shape[1]
        blk = jnp.take_along_axis(table, pos // bs, axis=1)     # [b, t]
        off = pos % bs
        blk = jnp.where(write_ok, blk, 0)    # padding → trash block
        off = jnp.where(write_ok, off, 0)
        new_pool = dict(pool)
        if "k_scale" in pool:
            # quantized pool (nn/quantize.py): per-(position, head)
            # scales over head_dim — quantize on scatter here, dequant
            # on gather below, attention math unchanged
            kq, ksc = kv_quantize(k, kp.dtype)
            vq, vsc = kv_quantize(v, vp.dtype)
            kp = kp.at[blk, off].set(kq)
            vp = vp.at[blk, off].set(vq)
            new_pool["k_scale"] = pool["k_scale"].at[blk, off].set(ksc)
            new_pool["v_scale"] = pool["v_scale"].at[blk, off].set(vsc)
        else:
            kp = kp.at[blk, off].set(k.astype(kp.dtype))
            vp = vp.at[blk, off].set(v.astype(vp.dtype))
        new_pool["k"], new_pool["v"] = kp, vp
        kg = jnp.take(kp, table, axis=0).reshape(b, mb * bs, *kp.shape[2:])
        vg = jnp.take(vp, table, axis=0).reshape(b, mb * bs, *vp.shape[2:])
        if "k_scale" in pool:
            ksg = jnp.take(new_pool["k_scale"], table, axis=0).reshape(
                b, mb * bs, h_count)
            vsg = jnp.take(new_pool["v_scale"], table, axis=0).reshape(
                b, mb * bs, h_count)
            kg = kv_dequantize(kg, ksg, q.dtype)
            vg = kv_dequantize(vg, vsg, q.dtype)
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, q.dtype))
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kg.astype(q.dtype)) * scale
        live = jnp.arange(mb * bs)[None, None, :] <= pos[:, :, None]
        s = jnp.where(live[:, None], s,
                      jnp.asarray(jnp.finfo(s.dtype).min, s.dtype))
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhqk,bkhd->bqhd", w, vg.astype(q.dtype))
        x = self._slice_replicate(
            x + qmatmul(self._slice_replicate(o.reshape(b, t, d)),
                        params, "Wo"))
        h2 = _layer_norm(x, params["ln2_g"], params["ln2_b"])
        mlp, _ = self._ffn(params, h2.reshape(-1, d), {},
                           capacity_factor=float(max(1, c.num_experts)))
        return self._slice_replicate(x + mlp.reshape(b, t, d)), new_pool

    def decode_step(self, params, x_t, cache, pos, write_mask=None):
        """One-token forward [b, d] with cached keys/values; ``pos`` is
        the (traced) current position — a scalar (whole-batch position)
        or a [b] vector (per-row positions, the ragged-prompt serving
        path; the K/V write becomes a per-row one-hot scatter). Returns
        (y_t [b, d], new cache). Dense blocks match ``forward`` exactly
        at every prefix position (tested); MoE blocks route NO-DROP at
        decode time (capacity = batch) — the training-time capacity
        heuristic over b*t tokens has no stepwise equivalent, and
        dropping tokens at inference is never what serving wants.

        **Paged mode** (the vLLM PagedAttention layout, nn/kvpool.py):
        when ``cache`` carries a ``"table"`` entry, ``cache["k"]`` /
        ``cache["v"]`` are the SHARED pool buffers
        ``[num_blocks, block_size, h, hd]`` and ``cache["table"]`` is
        the per-row block table ``[b, max_blocks]`` of pool indices.
        The K/V write scatters into (table[pos // bs], pos % bs) and
        attention gathers the row's blocks back into causal order;
        ``write_mask`` [b] bool redirects masked rows' writes to the
        reserved trash block 0, so retired rows / batch-slot padding /
        warmup dispatches can never scribble over a live sequence's
        blocks. ``pos`` must be a [b] vector in paged mode."""
        c = self.conf
        b, d = x_t.shape
        h_count, hd = c.num_heads, c.n_out // c.num_heads
        h = _layer_norm(x_t, params["ln1_g"], params["ln1_b"])
        qkv = qmatmul(h, params, "Wqkv")
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = lambda z: z.reshape(b, h_count, hd)
        q, k, v = shape(q), shape(k), shape(v)
        if "table" in cache:
            return self._decode_step_paged(params, x_t, cache, pos,
                                           q, k, v, write_mask)
        slots = jnp.arange(cache["k"].shape[1])
        if jnp.ndim(pos) == 0:
            ck = jax.lax.dynamic_update_slice_in_dim(
                cache["k"], k[:, None].astype(cache["k"].dtype), pos, axis=1)
            cv = jax.lax.dynamic_update_slice_in_dim(
                cache["v"], v[:, None].astype(cache["v"].dtype), pos, axis=1)
            # causal: only positions <= pos are live
            live = (slots <= pos)[None, :]
        else:
            sel = (slots[None, :] == pos[:, None])[:, :, None, None]
            ck = jnp.where(sel, k[:, None].astype(cache["k"].dtype),
                           cache["k"])
            cv = jnp.where(sel, v[:, None].astype(cache["v"].dtype),
                           cache["v"])
            live = slots[None, :] <= pos[:, None]  # [b, L] per-row causal
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, q.dtype))
        s = jnp.einsum("bhd,bkhd->bhk", q, ck.astype(q.dtype)) * scale
        s = jnp.where(live[:, None, :], s,
                      jnp.asarray(jnp.finfo(s.dtype).min, s.dtype))
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhk,bkhd->bhd", w, cv.astype(q.dtype))
        x_t = self._slice_replicate(
            x_t + qmatmul(self._slice_replicate(o.reshape(b, d)),
                          params, "Wo"))

        h2 = _layer_norm(x_t, params["ln2_g"], params["ln2_b"])
        # no-drop capacity: capacity = ceil(cf*b/E) >= b when cf = E
        mlp, _ = self._ffn(params, h2, {},
                           capacity_factor=float(max(1, c.num_experts)))
        return self._slice_replicate(x_t + mlp), {"k": ck, "v": cv}

    def _decode_step_paged(self, params, x_t, cache, pos, q, k, v,
                           write_mask):
        """Gather/scatter attention over a block table (decode_step's
        paged-pool branch — q/k/v already projected): scatter this
        token's K/V into its row's (block, offset) pool slot, gather
        the row's blocks back as a contiguous [b, MB*bs] view, and run
        the same masked softmax attention as the dense branch. Gathered
        positions past ``pos`` (including every trash/garbage block the
        table pads with) are causally masked, so pool garbage is
        numerically inert exactly like the dense path's padded tail.

        A QUANTIZED pool (``"k_scale"``/``"v_scale"`` entries — the
        nn/kvpool.py int8/fp8 variant) quantizes the incoming token's
        K/V per head on the scatter and dequantizes the gathered view
        before the softmax; everything else — table discipline, trash
        redirect, causal mask — is identical, and the scale arrays ride
        the same (block, offset) addressing as the values."""
        c = self.conf
        b, d = x_t.shape
        kp, vp = cache["k"], cache["v"]      # [NB, bs, h, hd] shared pool
        table = cache["table"]               # [b, MB] int32 block ids
        bs = kp.shape[1]
        mb = table.shape[1]
        blk_of = pos // bs
        off = pos % bs
        blk = jnp.take_along_axis(table, blk_of[:, None], axis=1)[:, 0]
        if write_mask is not None:
            # masked rows write the trash block — never a live sequence
            blk = jnp.where(write_mask, blk, 0)
            off = jnp.where(write_mask, off, 0)
        new_cache = dict(cache)
        if "k_scale" in cache:
            kq, ksc = kv_quantize(k, kp.dtype)
            vq, vsc = kv_quantize(v, vp.dtype)
            kp = kp.at[blk, off].set(kq)
            vp = vp.at[blk, off].set(vq)
            new_cache["k_scale"] = cache["k_scale"].at[blk, off].set(ksc)
            new_cache["v_scale"] = cache["v_scale"].at[blk, off].set(vsc)
        else:
            kp = kp.at[blk, off].set(k.astype(kp.dtype))
            vp = vp.at[blk, off].set(v.astype(vp.dtype))
        new_cache["k"], new_cache["v"] = kp, vp
        # gather the row's cache back into causal order: [b, MB*bs, h, hd]
        kg = jnp.take(kp, table, axis=0).reshape(b, mb * bs, *kp.shape[2:])
        vg = jnp.take(vp, table, axis=0).reshape(b, mb * bs, *vp.shape[2:])
        if "k_scale" in cache:
            h_count = c.num_heads
            ksg = jnp.take(new_cache["k_scale"], table, axis=0).reshape(
                b, mb * bs, h_count)
            vsg = jnp.take(new_cache["v_scale"], table, axis=0).reshape(
                b, mb * bs, h_count)
            kg = kv_dequantize(kg, ksg, q.dtype)
            vg = kv_dequantize(vg, vsg, q.dtype)
        hd = c.n_out // c.num_heads
        scale = 1.0 / jnp.sqrt(jnp.asarray(hd, q.dtype))
        s = jnp.einsum("bhd,bkhd->bhk", q, kg.astype(q.dtype)) * scale
        live = jnp.arange(mb * bs)[None, :] <= pos[:, None]
        s = jnp.where(live[:, None, :], s,
                      jnp.asarray(jnp.finfo(s.dtype).min, s.dtype))
        w = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhk,bkhd->bhd", w, vg.astype(q.dtype))
        x_t = self._slice_replicate(
            x_t + qmatmul(self._slice_replicate(o.reshape(b, d)),
                          params, "Wo"))

        h2 = _layer_norm(x_t, params["ln2_g"], params["ln2_b"])
        mlp, _ = self._ffn(params, h2, {},
                           capacity_factor=float(max(1, c.num_experts)))
        return self._slice_replicate(x_t + mlp), new_cache
