"""Layer implementations: pure ``init_params`` / ``forward`` pairs.

Rebuild of ``nn/layers/`` (SURVEY.md §2.1). Design difference from the
reference: DL4J layers are stateful objects holding activations for
backprop; here each impl is a pair of pure functions and the container
differentiates the whole composed forward with ``jax.grad`` — there is no
hand-written ``backpropGradient`` (XLA derives and fuses it), and the
cuDNN helper seam (``ConvolutionHelper.java:30``) has no analog because
XLA emits TPU kernels for conv/pool/norm directly.
"""

from deeplearning4j_tpu.nn.layers.base import LayerImpl, build_layer  # noqa: F401
from deeplearning4j_tpu.nn.layers import (  # noqa: F401  (registers impls)
    attention,
    convolution,
    feedforward,
    moe,
    normalization,
    recurrent,
    transformer,
)
