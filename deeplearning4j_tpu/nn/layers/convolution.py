"""Convolution + subsampling (pooling) layers.

Parity: ``nn/layers/convolution/ConvolutionLayer.java:45`` and
``subsampling/SubsamplingLayer.java:50`` plus their cuDNN helpers
(``CudnnConvolutionHelper.java:51``, ``CudnnSubsamplingHelper.java``).

TPU-first: the reference's im2col + gemm (CPU) / cuDNN descriptor-and-
workspace machinery (GPU) collapses into a single
``lax.conv_general_dilated`` / ``lax.reduce_window`` — XLA picks the MXU
tiling, so there is no algo-mode knob and no workspace management. NHWC
layout (TPU-native; the reference is NCHW).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import activate

_DIMS = ("NHWC", "HWIO", "NHWC")

# Maxpool backward selector, read ONCE at import (traced branches are
# baked into jitted executables, so flipping the env var mid-process
# would be silently ignored anyway): set DL4J_TPU_MAXPOOL_VJP=mask
# before the first import to opt into the equality-mask VJP.
import os as _os

_MAXPOOL_VJP = _os.environ.get("DL4J_TPU_MAXPOOL_VJP", "xla")


def _padding(conf) -> object:
    if getattr(conf, "convolution_mode", "truncate") == "same":
        return "SAME"
    ph, pw = conf.padding
    return [(ph, ph), (pw, pw)]


@register_impl(L.ConvolutionLayer)
class ConvolutionImpl(LayerImpl):
    supports_no_bias = True
    applies_drop_connect = True

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        c = self.conf
        kh, kw = c.kernel_size
        # receptive-field fans (ConvolutionParamInitializer convention)
        fan_in = c.n_in * kh * kw
        fan_out = c.n_out * kh * kw
        W = init_weights(key, (kh, kw, c.n_in, c.n_out), self.weight_init,
                         fan_in, fan_out, c.dist_mean, c.dist_std,
                         dist=c.dist)
        if not c.has_bias:
            return {"W": W}
        b = jnp.full((c.n_out,), self.bias_init, jnp.float32)
        return {"W": W, "b": b}

    def forward(self, params, x, state, train, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        params = self.maybe_drop_connect(params, train, rng)
        z = jax.lax.conv_general_dilated(
            x, params["W"].astype(x.dtype),
            window_strides=self.conf.stride,
            padding=_padding(self.conf),
            dimension_numbers=_DIMS,
        )
        if "b" in params:
            z = z + params["b"].astype(x.dtype)
        return activate(self.activation, z), state


@register_impl(L.SubsamplingLayer)
class SubsamplingImpl(LayerImpl):
    """Max/avg/sum/p-norm pooling via ``lax.reduce_window`` (the XLA op
    the cuDNN pooling descriptor becomes on TPU)."""

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        kh, kw = c.kernel_size
        sh, sw = c.stride
        ph, pw = c.padding
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pads = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        pt = c.pooling_type
        if pt == L.PoolingType.MAX:
            if (jnp.issubdtype(x.dtype, jnp.floating)
                    and _MAXPOOL_VJP == "mask"):
                # opt-in equality-mask backward (ops/pooling.py). It wins
                # the isolated stem-pool microbenchmark ~5x but LOSES
                # in-model: ResNet-50 full-step A/B on v5e measured
                # 49 ms/step (XLA SelectAndScatter grad) vs 69 ms/step
                # (mask VJP) — the kh*kw f32 dense passes break XLA's
                # fusion around the pool and add HBM traffic the
                # microbenchmark never saw. Default = XLA backward.
                from deeplearning4j_tpu.ops.pooling import maxpool2d
                out = maxpool2d(x, (kh, kw), (sh, sw), (ph, pw))
            else:
                init = (-jnp.inf if jnp.issubdtype(x.dtype, jnp.floating)
                        else jnp.iinfo(x.dtype).min)
                out = jax.lax.reduce_window(
                    x, init, jax.lax.max, window, strides, pads)
        elif pt in (L.PoolingType.AVG, L.PoolingType.SUM):
            out = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
            if pt == L.PoolingType.AVG:
                if ph or pw:
                    # true per-window cell count so padded border zeros
                    # don't bias averages low (count-include-pad=False).
                    # Deliberate deviation from the reference's im2col
                    # averaging (zero-filled windows / kh*kw), which
                    # undercounts borders — advisor-directed (ADVICE r1)
                    ones = jnp.ones((1,) + x.shape[1:3] + (1,), x.dtype)
                    cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add,
                                                window, strides, pads)
                    out = out / cnt
                else:
                    out = out / (kh * kw)
        elif pt == L.PoolingType.PNORM:
            p = float(c.pnorm)
            out = jax.lax.reduce_window(jnp.abs(x) ** p, 0.0, jax.lax.add, window, strides, pads)
            out = out ** (1.0 / p)
        else:
            raise ValueError(f"unknown pooling type {pt}")
        return out, state
