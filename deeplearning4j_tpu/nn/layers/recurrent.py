"""Recurrent layers: Graves LSTM (+ bidirectional).

Parity: ``nn/layers/recurrent/LSTMHelpers.java:43`` — Graves (2013)
LSTM with peephole connections. The reference runs an explicit Java
loop per timestep with one gemm each for forward (:131,:144) and a
second reverse loop for backprop (:272,:402). Here the recurrence is a
``lax.scan`` (XLA while-loop) over [b,t,f]; backprop-through-time is the
scan's transpose, generated and fused by XLA — the BASELINE.json
north-star "CudnnLSTMHelper → XLA while-loop" slot.

Param layout (vs ``GravesLSTMParamInitializer.java:95-112``): reference
packs input W [nIn, 4nL], recurrent W [nL, 4nL+3] (last 3 columns =
peepholes), bias [4nL]. Here peepholes are separate named params
(wci/wcf/wco) — same math, cleaner pytree. Gate order in the packed
4nL axis: [input, forget, output, block].

Masking: at masked timesteps the carry is held and the output zeroed
(variable-length semantics of ``TimeSeriesUtils``/masking tests).

``rnnTimeStep`` streaming state (``BaseRecurrentLayer`` stateMap) is the
(h, c) carry stored in the layer's non-trainable state.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import activate


def _lstm_params(key, n_in, n_out, weight_init, dist_mean, dist_std, forget_bias,
                 dist=None):
    kx, kr = jax.random.split(key)
    Wx = init_weights(kx, (n_in, 4 * n_out), weight_init, n_in, n_out,
                      dist_mean, dist_std, dist=dist)
    Wr = init_weights(kr, (n_out, 4 * n_out), weight_init, n_out, n_out,
                      dist_mean, dist_std, dist=dist)
    b = jnp.zeros((4 * n_out,), jnp.float32)
    # forget-gate section [n_out:2n_out] init (GravesLSTM.forgetGateBiasInit)
    b = b.at[n_out:2 * n_out].set(forget_bias)
    return {
        "Wx": Wx, "Wr": Wr, "b": b,
        "wci": jnp.zeros((n_out,), jnp.float32),
        "wcf": jnp.zeros((n_out,), jnp.float32),
        "wco": jnp.zeros((n_out,), jnp.float32),
    }


def _lstm_scan(p, x, h0, c0, gate_act: str, block_act: str, mask=None,
               reverse=False, train=True):
    """Run the LSTM over time. x: [b,t,f]; returns (outputs [b,t,n], (h,c)).

    One gemm per step on [b, 4n] (the reference's :144 gemm), with the
    input-to-gate projection for ALL timesteps hoisted out of the scan as
    a single [b*t, f]·[f, 4n] matmul — MXU-friendly: the big matmul is
    batched over time, only the small recurrent gemm stays sequential.

    Both inference AND training dispatch the recurrence to the fused
    Pallas kernels (``ops/lstm_kernel.py``) when the configuration
    allows: forward −32% vs this scan, and the r5 Pallas BPTT takes the
    full train step from 28.8% to 63.5% MFU at the char-RNN bench shape
    (BASELINE.md). Training additionally requires the backward kernel's
    VMEM budget (n ≤ 512); everything else keeps this XLA scan.
    """
    n = h0.shape[-1]
    xg = jnp.einsum("btf,fg->btg", x, p["Wx"]) + p["b"]  # [b,t,4n]
    xg_t = jnp.swapaxes(xg, 0, 1)  # [t,b,4n]

    from deeplearning4j_tpu.ops.lstm_kernel import (
        fused_lstm_applicable, fused_lstm_scan, fused_lstm_train_applicable)
    applicable = (fused_lstm_train_applicable if train
                  else fused_lstm_applicable)
    if applicable(x.shape[0], n, gate_act, block_act, mask,
                  itemsize=xg.dtype.itemsize):
        xg_k = xg_t[::-1] if reverse else xg_t
        h_seq, (h, c) = fused_lstm_scan(xg_k, p["Wr"], p["wci"], p["wcf"],
                                        p["wco"], h0, c0)
        if reverse:
            h_seq = h_seq[::-1]
        return jnp.swapaxes(h_seq, 0, 1), (h.astype(x.dtype),
                                           c.astype(x.dtype))

    mask_t = None if mask is None else jnp.swapaxes(mask, 0, 1)  # [t,b]

    def step(carry, inp):
        h, c = carry
        if mask_t is None:
            g = inp
            m = None
        else:
            g, m = inp
        g = g + h @ p["Wr"]
        i = activate(gate_act, g[:, :n] + c * p["wci"])
        f = activate(gate_act, g[:, n:2 * n] + c * p["wcf"])
        blk = activate(block_act, g[:, 3 * n:])
        c_new = f * c + i * blk
        o = activate(gate_act, g[:, 2 * n:3 * n] + c_new * p["wco"])
        h_new = o * activate(block_act, c_new)
        if m is not None:
            mm = m[:, None].astype(h_new.dtype)
            c_new = mm * c_new + (1 - mm) * c
            out = mm * h_new
            h_new = mm * h_new + (1 - mm) * h
        else:
            out = h_new
        return (h_new, c_new), out

    xs = xg_t if mask_t is None else (xg_t, mask_t)
    (h, c), out_t = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
    return jnp.swapaxes(out_t, 0, 1), (h, c)


@register_impl(L.GravesLSTM)
class GravesLSTMImpl(LayerImpl):
    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        return _lstm_params(key, c.n_in, c.n_out, self.weight_init,
                            c.dist_mean, c.dist_std, c.forget_gate_bias_init,
                            dist=c.dist)

    def init_state(self):
        # streaming (rnnTimeStep) carry; zeros mean "no history"
        return {}

    def forward(self, params, x, state, train, rng=None, mask=None):
        """When ``state`` carries an ("h","c") pair (TBPTT mode,
        ``doTruncatedBPTT`` :1175 / ``rnnActivateUsingStoredState``), the
        scan starts from it and the final carry is returned as the new
        state; otherwise zeros with no carry (standard mode)."""
        x = self.maybe_dropout_input(x, train, rng)
        b = x.shape[0]
        n = self.conf.n_out
        tbptt = isinstance(state, dict) and "h" in state
        h0 = state["h"].astype(x.dtype) if tbptt else jnp.zeros((b, n), x.dtype)
        c0 = state["c"].astype(x.dtype) if tbptt else jnp.zeros((b, n), x.dtype)
        out, (h, c) = _lstm_scan(params, x, h0, c0, self.conf.gate_activation,
                                 self.activation, mask, train=train)
        return out, ({"h": h, "c": c} if tbptt else state)

    def rnn_time_step(self, params, x, state):
        """Single-step stateful inference (``rnnTimeStep``,
        ``MultiLayerNetwork.java:1233`` stateMap semantics).
        x: [b, f] one timestep; state holds (h, c)."""
        b = x.shape[0]
        n = self.conf.n_out
        h = state.get("h", jnp.zeros((b, n), x.dtype))
        c = state.get("c", jnp.zeros((b, n), x.dtype))
        out, (h2, c2) = _lstm_scan(params, x[:, None, :], h, c,
                                   self.conf.gate_activation, self.activation,
                                   train=False)
        return out[:, 0, :], {"h": h2, "c": c2}


@register_impl(L.GravesBidirectionalLSTM)
class GravesBidirectionalLSTMImpl(LayerImpl):
    """Forward + backward LSTM, outputs summed
    (``GravesBidirectionalLSTM.java:218`` ``fwdOutput.addi(backOutput)``)."""

    def init_params(self, key):
        c = self.conf
        kf, kb = jax.random.split(key)
        pf = _lstm_params(kf, c.n_in, c.n_out, self.weight_init,
                          c.dist_mean, c.dist_std, c.forget_gate_bias_init,
                          dist=c.dist)
        pb = _lstm_params(kb, c.n_in, c.n_out, self.weight_init,
                          c.dist_mean, c.dist_std, c.forget_gate_bias_init,
                          dist=c.dist)
        return {**{f"f_{k}": v for k, v in pf.items()},
                **{f"b_{k}": v for k, v in pb.items()}}

    def forward(self, params, x, state, train, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        b = x.shape[0]
        n = self.conf.n_out
        pf = {k[2:]: v for k, v in params.items() if k.startswith("f_")}
        pb = {k[2:]: v for k, v in params.items() if k.startswith("b_")}
        h0 = jnp.zeros((b, n), x.dtype)
        c0 = jnp.zeros((b, n), x.dtype)
        out_f, _ = _lstm_scan(pf, x, h0, c0, self.conf.gate_activation,
                              self.activation, mask, train=train)
        out_b, _ = _lstm_scan(pb, x, h0, c0, self.conf.gate_activation,
                              self.activation, mask, reverse=True,
                              train=train)
        return out_f + out_b, state
