"""Feed-forward layer family: Dense, Output/RnnOutput/Loss, Embedding,
AutoEncoder, RBM, Activation, Dropout, GlobalPooling.

Parity anchors: ``nn/layers/feedforward/dense/DenseLayer.java``,
``nn/layers/BaseOutputLayer.java``, ``embedding/EmbeddingLayer.java``,
``autoencoder/AutoEncoder.java``, ``rbm/RBM.java`` (contrastive
divergence), ``nn/layers/BasePretrainNetwork.java``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl, apply_dropout
from deeplearning4j_tpu.nn.quantize import is_quantized, qmatmul, qtake
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.activations import Activation, activate
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss


def _fused_logits_pair(activation: str, loss_function: str) -> bool:
    """True when activation+loss compute via the numerically-stable fused
    from-logits path (identical math, one fewer HBM round-trip)."""
    act = Activation(activation)
    lf = LossFunction(loss_function)
    return (act is Activation.SOFTMAX and lf in (LossFunction.MCXENT,
                                                 LossFunction.NEGATIVELOGLIKELIHOOD)) or \
           (act is Activation.SIGMOID and lf is LossFunction.XENT)


class BaseDenseImpl(LayerImpl):
    """z = x·W + b ; a = act(z) (``BaseLayer.preOutput`` :354)."""

    supports_no_bias = True
    applies_drop_connect = True

    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        c = self.conf
        kW, _ = jax.random.split(key)
        W = init_weights(kW, (c.n_in, c.n_out), self.weight_init, c.n_in, c.n_out,
                         c.dist_mean, c.dist_std, dist=c.dist)
        if not c.has_bias:
            return {"W": W}
        b = jnp.full((c.n_out,), self.bias_init, jnp.float32)
        return {"W": W, "b": b}

    def preout(self, params, x):
        # serving-slice seam: a previous column-sharded dense layer left
        # x sharded on its feature dim — all-gather before W contracts
        # over it, so the contraction never reduces across shards
        x = self._slice_replicate(x)
        if is_quantized(params, "W"):
            # int8/fp8 weights: dequant fused into the matmul
            # (nn/quantize.py) — bias added in the scaled dtype
            z = qmatmul(x, params, "W")
            return z + params["b"].astype(z.dtype) if "b" in params else z
        z = x @ params["W"]
        return z + params["b"] if "b" in params else z

    def forward(self, params, x, state, train, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        params = self.maybe_drop_connect(params, train, rng)
        return activate(self.activation, self.preout(params, x)), state


@register_impl(L.DenseLayer)
class DenseImpl(BaseDenseImpl):
    pass


@register_impl(L.OutputLayer)
class OutputImpl(BaseDenseImpl):
    """Dense + loss (``nn/layers/OutputLayer.java``). Scoring uses the
    fused from-logits path when activation/loss pair allows (softmax+
    mcxent/nll, sigmoid+xent) — numerically identical, XLA-fused."""

    def has_loss(self) -> bool:
        return True

    def preout(self, params, x):
        # OUTPUT-HEAD override only (hidden dense layers keep their
        # policy dtype end to end): on half-precision operands the head
        # matmul stays at full MXU rate but the logits land in f32, so
        # all loss math keeps the documented always-f32 guarantee.
        # Higher-precision models (incl. the f64 gradcheck oracle) keep
        # their native matmul — forcing f32 there would DOWNcast.
        x = self._slice_replicate(x)
        W = params["W"]
        if is_quantized(params, "W"):
            # quantized head: int8/fp8 matmul operand, scale fused
            # after; logits land in f32 downstream (the generate-path
            # _head_logits / loss casts), matching the always-f32 rule
            # within the quantized numeric contract
            z = qmatmul(x, params, "W")
        elif jnp.promote_types(x.dtype, W.dtype) in (jnp.bfloat16,
                                                     jnp.float16):
            z = jnp.matmul(x, W, preferred_element_type=jnp.float32)
        else:
            z = x @ W
        return z + params["b"].astype(z.dtype) if "b" in params else z

    @property
    def loss_function(self) -> str:
        return self.conf.loss_function

    def score(self, params, x, labels, state, train, rng=None, mask=None):
        """Mean-over-examples data loss for this output layer."""
        x = self.maybe_dropout_input(x, train, rng)
        params = self.maybe_drop_connect(params, train, rng)
        z = self.preout(params, x)
        if _fused_logits_pair(self.activation, self.loss_function):
            return compute_loss(self.loss_function, labels, z, mask=mask, from_logits=True)
        return compute_loss(self.loss_function, labels, activate(self.activation, z), mask=mask)


@register_impl(L.RnnOutputLayer)
class RnnOutputImpl(OutputImpl):
    """Per-timestep output over [b, t, f] inputs
    (``nn/layers/recurrent/RnnOutputLayer.java``); the label mask is
    [b, t]. The dense transform broadcasts over the time axis."""


@register_impl(L.LossLayer)
class LossImpl(LayerImpl):
    """``nn/layers/LossLayer.java`` — parameterless activation + loss."""

    def has_loss(self) -> bool:
        return True

    @property
    def loss_function(self) -> str:
        return self.conf.loss_function

    def forward(self, params, x, state, train, rng=None, mask=None):
        return activate(self.activation, x), state

    def score(self, params, x, labels, state, train, rng=None, mask=None):
        if _fused_logits_pair(self.activation, self.loss_function):
            return compute_loss(self.loss_function, labels, x, mask=mask, from_logits=True)
        return compute_loss(self.loss_function, labels,
                            activate(self.activation, x), mask=mask)


@register_impl(L.EmbeddingLayer)
class EmbeddingImpl(LayerImpl):
    """``nn/layers/feedforward/embedding/EmbeddingLayer.java`` — index
    lookup. Input: int indices [b] or [b, 1]; output [b, n_out].
    jnp.take lowers to a TPU gather; bias added as in the reference."""

    cast_input = False  # ids must stay exact (see LayerImpl.cast_input)

    def init_params(self, key):
        c = self.conf
        W = init_weights(key, (c.n_in, c.n_out), self.weight_init, c.n_in, c.n_out,
                         c.dist_mean, c.dist_std, dist=c.dist)
        b = jnp.full((c.n_out,), self.bias_init, jnp.float32)
        return {"W": W, "b": b}

    def forward(self, params, x, state, train, rng=None, mask=None):
        idx = x.astype(jnp.int32)
        if idx.ndim == 2:
            idx = idx[:, 0]
        z = qtake(params, "W", idx) + params["b"]
        return activate(self.activation, z), state


@register_impl(L.ActivationLayer)
class ActivationImpl(LayerImpl):
    def forward(self, params, x, state, train, rng=None, mask=None):
        return activate(self.activation, x), state


@register_impl(L.DropoutLayer)
class DropoutImpl(LayerImpl):
    def forward(self, params, x, state, train, rng=None, mask=None):
        rate = self.dropout_rate
        if train and rate > 0.0 and rng is not None:
            x = apply_dropout(x, rate, rng)
        return x, state


@register_impl(L.GlobalPoolingLayer)
class GlobalPoolingImpl(LayerImpl):
    """Pool over time ([b,t,f] -> [b,f], honoring the feature mask) or
    space ([b,h,w,c] -> [b,c])."""

    def forward(self, params, x, state, train, rng=None, mask=None):
        pt = self.conf.pooling_type
        if x.ndim == 3:
            if mask is not None:
                m = mask[:, :, None].astype(x.dtype)
                if pt == L.PoolingType.MAX:
                    big_neg = jnp.asarray(-1e30, x.dtype)
                    return jnp.max(jnp.where(m > 0, x, big_neg), axis=1), state
                if pt == L.PoolingType.PNORM:
                    p = self.conf.pnorm
                    s = jnp.sum(jnp.power(jnp.abs(x) * m, p), axis=1)
                    return jnp.power(s, 1.0 / p), state
                s = jnp.sum(x * m, axis=1)
                if pt == L.PoolingType.SUM:
                    return s, state
                return s / jnp.maximum(jnp.sum(m, axis=1), 1.0), state
            axis = (1,)
        else:
            axis = (1, 2)
        if pt == L.PoolingType.MAX:
            return jnp.max(x, axis=axis), state
        if pt == L.PoolingType.SUM:
            return jnp.sum(x, axis=axis), state
        if pt == L.PoolingType.PNORM:
            p = self.conf.pnorm
            return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis), 1.0 / p), state
        return jnp.mean(x, axis=axis), state


@register_impl(L.AutoEncoder)
class AutoEncoderImpl(BaseDenseImpl):
    """Denoising autoencoder (``nn/layers/feedforward/autoencoder/
    AutoEncoder.java``): encode a = act(xW+b), decode x' = act(aWᵀ+vb);
    pretrain loss is reconstruction of the *uncorrupted* input."""

    def init_params(self, key):
        p = super().init_params(key)
        p["vb"] = jnp.zeros((self.conf.n_in,), jnp.float32)  # visible bias
        return p

    def encode(self, params, x):
        return activate(self.activation, x @ params["W"] + params["b"])

    def decode(self, params, a):
        return activate(self.activation, a @ params["W"].T + params["vb"])

    def forward(self, params, x, state, train, rng=None, mask=None):
        return self.encode(params, x), state

    def pretrain_loss(self, params, x, rng):
        c = self.conf
        corrupted = x
        if c.corruption_level > 0.0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - c.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        recon = self.decode(params, self.encode(params, corrupted))
        loss = compute_loss(c.loss_function, x, recon)
        if c.sparsity > 0.0:
            a_mean = jnp.mean(self.encode(params, x), axis=0)
            loss = loss + jnp.sum((a_mean - c.sparsity) ** 2)
        return loss


@register_impl(L.RBM)
class RBMImpl(BaseDenseImpl):
    """Restricted Boltzmann machine with CD-k pretraining
    (``nn/layers/feedforward/rbm/RBM.java``).

    TPU formulation: the positive/negative phases are batched matmuls and
    the Gibbs chain is a ``lax.scan`` of length k (static), so the whole
    CD update is one XLA program — the reference ran a host loop of ND4J
    calls per step. The CD gradient is supplied directly (not via
    jax.grad; contrastive divergence is not the gradient of a tractable
    objective).
    """

    def init_params(self, key):
        p = super().init_params(key)
        p["vb"] = jnp.zeros((self.conf.n_in,), jnp.float32)
        return p

    def _prop_up(self, params, v):
        z = v @ params["W"] + params["b"]
        return jax.nn.sigmoid(z) if self.conf.hidden_unit == L.RBMHiddenUnit.BINARY else jax.nn.relu(z)

    def _prop_down(self, params, h):
        z = h @ params["W"].T + params["vb"]
        vu = self.conf.visible_unit
        if vu == L.RBMVisibleUnit.BINARY:
            return jax.nn.sigmoid(z)
        if vu == L.RBMVisibleUnit.SOFTMAX:
            return jax.nn.softmax(z, axis=-1)
        return z  # gaussian / linear: mean-field identity

    def forward(self, params, x, state, train, rng=None, mask=None):
        x = self.maybe_dropout_input(x, train, rng)
        return activate(self.activation, x @ params["W"] + params["b"]), state

    def cd_gradients(self, params, v0, rng):
        """CD-k gradient estimate + reconstruction error, all in-step."""
        c = self.conf
        h0 = self._prop_up(params, v0)

        def gibbs(carry, key):
            h, _ = carry
            hs = jax.random.bernoulli(key, h).astype(v0.dtype) \
                if c.hidden_unit == L.RBMHiddenUnit.BINARY else h
            v = self._prop_down(params, hs)
            return (self._prop_up(params, v), v), None

        keys = jax.random.split(rng, c.k)
        (hk, vk), _ = jax.lax.scan(gibbs, (h0, v0), keys)
        n = v0.shape[0]
        gW = -(v0.T @ h0 - vk.T @ hk) / n
        gb = -jnp.mean(h0 - hk, axis=0)
        gvb = -jnp.mean(v0 - vk, axis=0)
        recon_err = compute_loss(c.loss_function, v0, jnp.clip(vk, 1e-7, 1 - 1e-7))
        return {"W": gW, "b": gb, "vb": gvb}, recon_err

    def pretrain_loss(self, params, x, rng):
        # used only for score reporting; gradients come from cd_gradients
        _, err = self.cd_gradients(params, x, rng)
        return err
