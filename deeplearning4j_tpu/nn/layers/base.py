"""Layer implementation protocol + registry + shared helpers.

Parity anchor: ``nn/layers/BaseLayer.java`` (preOutput :354,
backpropGradient :145 — the latter intentionally absent here, see package
docstring) and ``util/Dropout.java``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple, Type

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.conf.configuration import NeuralNetConfiguration

_IMPL_REGISTRY: Dict[Type[L.Layer], Type["LayerImpl"]] = {}


def register_impl(conf_cls: Type[L.Layer]):
    def deco(impl_cls):
        _IMPL_REGISTRY[conf_cls] = impl_cls
        impl_cls.conf_cls = conf_cls
        return impl_cls

    return deco


def build_layer(global_conf: NeuralNetConfiguration, layer_conf: L.Layer, name: str) -> "LayerImpl":
    """Instantiate the impl for a layer config (the reference resolved this
    via ``Layer.instantiate``; custom layers register with
    :func:`register_impl`)."""
    for cls in type(layer_conf).__mro__:
        if cls in _IMPL_REGISTRY:
            return _IMPL_REGISTRY[cls](global_conf, layer_conf, name)
    raise ValueError(f"no implementation registered for {type(layer_conf).__name__}")


def apply_dropout(x: jnp.ndarray, rate: float, rng: jax.Array) -> jnp.ndarray:
    """Inverted dropout (``util/Dropout.java``): each unit dropped with
    probability ``rate``, survivors scaled by 1/(1-rate) so inference
    needs no rescale."""
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


class LayerImpl:
    """A layer = pure ``init_params`` + ``forward``.

    ``forward(params, x, state, train, rng) -> (out, new_state)``.
    ``state`` carries non-trainable variables (batch-norm moving stats,
    RNN last-step carry for ``rnnTimeStep``); pure so the container can
    trace it into one XLA program.
    """

    conf_cls: Type[L.Layer] = L.Layer

    # False for layers whose input is integer indices (embeddings): the
    # mixed-precision input cast must NOT touch them — bf16 has an
    # 8-bit mantissa, so ids >= 256 round (bf16(511) == 512), producing
    # wrong or out-of-range gathers/scatter-grads
    cast_input = True

    # Impls that honor ``has_bias=False`` set this True (conv/dense); all
    # others reject the flag loudly instead of silently training a bias.
    supports_no_bias = False

    # True for layers whose train-mode output/loss depends on CROSS-batch
    # statistics (batch-norm moments, MoE load-balancing aux loss): the
    # shape-bucketing tail-batch padding is only exact for per-example-
    # independent layers, so the containers skip padding when any layer
    # sets this.
    batch_statistics = False

    # Serving-slice seam (parallel/mesh.py apply_serving_slice): when a
    # net is placed on a mesh SLICE with the column-only tensor-parallel
    # layout, every impl gets its slice mesh pinned here, and the impl's
    # traced code calls :meth:`_slice_replicate` right before any
    # reduction that would otherwise cross shards (a LayerNorm mean over
    # a sharded feature dim, a matmul contracting a sharded activation).
    # The constraint lowers to an all-gather — pure data movement — so
    # sliced output stays BITWISE equal to the single-device program.
    # None (the default) keeps every existing path byte-identical.
    _slice_mesh = None

    def _slice_replicate(self, x):
        """Constrain ``x`` to replicated over the slice mesh (identity
        when the net is not slice-served)."""
        mesh = self._slice_mesh
        if mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, PartitionSpec()))

    def __init__(self, global_conf: NeuralNetConfiguration, conf: L.Layer, name: str):
        self.gc = global_conf
        self.conf = conf
        self.name = name
        if not getattr(conf, "has_bias", True) and not self.supports_no_bias:
            raise ValueError(
                f"{type(conf).__name__} ({name}): has_bias=False is not "
                f"supported by {type(self).__name__}")

    # -- config resolution helpers --
    @property
    def activation(self) -> str:
        return self.conf.activation or self.gc.activation

    @property
    def weight_init(self) -> str:
        return self.conf.weight_init or self.gc.weight_init

    @property
    def bias_init(self) -> float:
        return self.conf.bias_init if self.conf.bias_init is not None else self.gc.bias_init

    @property
    def dropout_rate(self) -> float:
        return self.conf.dropout if self.conf.dropout is not None else self.gc.dropout

    @property
    def l1(self) -> float:
        return self.conf.l1 if self.conf.l1 is not None else self.gc.l1

    @property
    def l2(self) -> float:
        return self.conf.l2 if self.conf.l2 is not None else self.gc.l2

    # -- protocol --
    def init_params(self, key: jax.Array) -> Dict[str, jnp.ndarray]:
        return {}

    def init_state(self) -> Dict[str, Any]:
        return {}

    def num_params(self) -> int:
        import numpy as np

        key = jax.random.PRNGKey(0)
        return int(sum(np.prod(v.shape) for v in self.init_params(key).values()))

    def forward(
        self,
        params: Dict[str, jnp.ndarray],
        x: jnp.ndarray,
        state: Dict[str, Any],
        train: bool,
        rng: Optional[jax.Array] = None,
        mask: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Dict[str, Any]]:
        raise NotImplementedError

    def maybe_dropout_input(self, x: jnp.ndarray, train: bool, rng: Optional[jax.Array]) -> jnp.ndarray:
        """The reference applies dropout to a layer's *input* activations
        (``BaseLayer.preOutput`` → ``Dropout.applyDropout``) — UNLESS
        DropConnect is on, which redirects the same probability to the
        weights instead (``BaseLayer.java:449`` has ``!useDropConnect``
        in the input-dropout condition)."""
        rate = self.dropout_rate
        if (train and rate > 0.0 and rng is not None
                and not (self.applies_drop_connect
                         and getattr(self.gc, "use_drop_connect", False))):
            return apply_dropout(x, rate, rng)
        return x

    # True only for impls whose forward actually calls maybe_drop_connect
    # (dense family, conv, output — the layers where the reference's
    # BaseLayer.preOutput/ConvolutionLayer apply it). Layers WITHOUT the
    # weight-mask path keep their input dropout even under
    # use_drop_connect, so the flag can never silently strip a layer's
    # only stochastic regularization (review r4).
    applies_drop_connect = False

    def maybe_drop_connect(self, params: Dict[str, jnp.ndarray], train: bool,
                           rng: Optional[jax.Array]) -> Dict[str, jnp.ndarray]:
        """DropConnect (``BaseLayer.preOutput:350``,
        ``ConvolutionLayer.java:189`` → ``util/Dropout.java:13``
        ``applyDropConnect``): with ``use_drop_connect``, the layer's
        dropout probability masks the WEIGHT matrix (W only — biases are
        untouched, matching the reference's WEIGHT_KEY-only call).
        Inverted scaling (survivors / keep) like this framework's input
        dropout, so inference needs no rescale."""
        rate = self.dropout_rate
        if not (train and rate > 0.0 and rng is not None and "W" in params
                and getattr(self.gc, "use_drop_connect", False)):
            return params
        # distinct stream from any input-dropout use of the same rng
        key = jax.random.fold_in(rng, 0x0D20)
        return {**params, "W": apply_dropout(params["W"], rate, key)}

    def regularization_penalty(self, params: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        """L1/L2 score term (``BaseLayer.calcL2/calcL1``; weights only, not
        biases — reference convention)."""
        pen = jnp.asarray(0.0, jnp.float32)
        if self.l2 > 0.0:
            for k, v in params.items():
                if k != "b":
                    pen = pen + 0.5 * self.l2 * jnp.sum(v.astype(jnp.float32) ** 2)
        if self.l1 > 0.0:
            for k, v in params.items():
                if k != "b":
                    pen = pen + self.l1 * jnp.sum(jnp.abs(v.astype(jnp.float32)))
        return pen

    def has_loss(self) -> bool:
        return False
