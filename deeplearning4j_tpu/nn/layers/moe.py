"""Mixture-of-experts layer impl (expert parallelism).

No reference counterpart (SURVEY §2.6 lists expert parallelism as
absent from the reference); the routing math lives in ``ops/moe.py``.
Expert parallelism is a sharding, not a code path: put
``PartitionSpec("expert", ...)`` on the leading dim of W1/b1/W2/b2
(``parallel.tensor_parallel.moe_ep_specs``) and XLA lowers the
dispatch/combine einsums to the canonical all-to-all over the mesh —
the forward below never mentions devices.

The Switch load-balancing aux loss is activation-dependent, so it
can't flow through ``regularization_penalty(params)``; instead it
rides the layer-state seam: ``forward`` writes the weighted aux into
``state["__aux_loss__"]`` and the containers add every such entry to
the training objective (differentiably — state is produced inside the
traced step).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.moe import moe_ffn

AUX_LOSS_KEY = "__aux_loss__"


def init_moe_params(key, d: int, f: int, e: int, weight_init: str,
                    dist_mean: float, dist_std: float,
                    dist=None) -> Dict[str, jnp.ndarray]:
    """Router + expert FFN weights (shared by MoEImpl and the MoE
    variant of TransformerBlock)."""
    ks = jax.random.split(key, 3)
    mk = lambda k, shape, fi, fo: init_weights(
        k, shape, weight_init, fi, fo, dist_mean, dist_std, dist=dist)
    return {
        "Wg": mk(ks[0], (d, e), d, e),
        "W1": mk(ks[1], (e, d, f), d, f),
        "b1": jnp.zeros((e, f), jnp.float32),
        "W2": mk(ks[2], (e, f, d), f, d),
        "b2": jnp.zeros((e, d), jnp.float32),
    }


def run_moe_ffn(params, x2: jnp.ndarray, capacity_factor: float,
                aux_loss_weight: float, mask=None):
    """Flattened-token MoE forward + weighted aux packaged for the
    layer-state seam: returns (y2, {AUX_LOSS_KEY: weighted_aux})."""
    valid = mask.reshape(-1) if mask is not None else None
    y2, aux = moe_ffn(x2, params["Wg"], params["W1"], params["b1"],
                      params["W2"], params["b2"],
                      capacity_factor=capacity_factor, valid=valid)
    return y2, {AUX_LOSS_KEY: aux_loss_weight * aux.astype(jnp.float32)}


@register_impl(L.MoELayer)
class MoEImpl(LayerImpl):
    batch_statistics = True  # load-balancing aux loss + expert capacity
    # are batch-level quantities: padded rows would skew both, so
    # shape-bucketing tail padding is gated off for MoE stacks

    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        if c.n_out != c.n_in:
            raise ValueError("MoELayer needs n_in == n_out (FFN block)")
        return init_moe_params(key, c.n_in, c.ffn_mult * c.n_in,
                               c.num_experts, self.weight_init,
                               c.dist_mean, c.dist_std, dist=c.dist)

    def init_state(self):
        return {AUX_LOSS_KEY: jnp.zeros((), jnp.float32)}

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        x = self.maybe_dropout_input(x, train, rng)
        shape = x.shape
        if x.ndim == 3:
            x2 = x.reshape(-1, shape[-1])
        elif x.ndim == 2:
            x2 = x
        else:
            raise ValueError(f"MoELayer needs [b, d] or [b, t, d], got {shape}")
        # masked timesteps must not occupy capacity or skew the aux
        routing_mask = mask if (mask is not None and x.ndim == 3) else None
        y2, new_state = run_moe_ffn(params, x2, c.capacity_factor,
                                    c.aux_loss_weight, mask=routing_mask)
        y = y2.reshape(shape[:-1] + (c.n_out,))
        if c.residual:
            y = y + x
        if mask is not None and y.ndim == 3:
            y = y * mask[:, :, None].astype(y.dtype)
        return y, new_state
