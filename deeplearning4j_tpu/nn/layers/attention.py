"""Multi-head self-attention layer impl.

No reference counterpart (SURVEY.md §5: the reference's only
long-context tool is truncated BPTT); this makes the round-1 orphan
``ops/attention.py`` capability user-reachable as a layer (VERDICT r1
next-round #8) and is the on-ramp to sequence parallelism: when a
``parallel.mesh.sequence_mesh`` context is active the forward switches
to the ring-attention kernel (``parallel/ring_attention.py``), sharding
time over the mesh's ``seq`` axis with K/V blocks rotating over ICI.
"""

from __future__ import annotations

import contextlib
from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl
from deeplearning4j_tpu.nn.weights import init_weights
from deeplearning4j_tpu.ops.attention import scaled_dot_product_attention
from deeplearning4j_tpu.ops.flash_attention import flash_attention
from deeplearning4j_tpu.parallel.mesh import current_sequence_mesh
from deeplearning4j_tpu.parallel.ring_attention import ring_attention

_FORCE_XLA: list = []


@contextlib.contextmanager
def xla_attention():
    """Force the plain XLA attention formulation while tracing under
    this context. Needed where a Pallas call can't apply — notably
    inside the pipeline-parallel ``shard_map`` (pallas_call outputs
    carry no varying-mesh-axes info, and pp stages hold short
    per-microbatch activations where flash's memory advantage is moot
    anyway)."""
    _FORCE_XLA.append(True)
    try:
        yield
    finally:
        _FORCE_XLA.pop()


def dispatch_attention(q, k, v, causal: bool, mask=None):
    """Shared parallelism dispatch for every attention-bearing layer:
    ring attention under an active sequence mesh (DP×SP when the mesh
    also has a 'data' axis), otherwise the flash Pallas kernel
    (key-validity masks fall back to the XLA path inside it; ring
    blocks assume dense time, so masked inputs also stay off the ring).
    An active ``xla_attention()`` context overrides both."""
    if _FORCE_XLA:
        return scaled_dot_product_attention(q, k, v, causal=causal, mask=mask)
    seq = current_sequence_mesh()
    if seq is not None and mask is None:
        mesh, axis = seq
        batch_axis = "data" if "data" in mesh.shape else None
        return ring_attention(q, k, v, mesh, axis=axis, causal=causal,
                              batch_axis=batch_axis)
    return flash_attention(q, k, v, causal=causal, mask=mask)


@register_impl(L.AttentionLayer)
class AttentionImpl(LayerImpl):
    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        if c.n_out % c.num_heads != 0:
            raise ValueError(f"n_out {c.n_out} not divisible by num_heads {c.num_heads}")
        kq, kk, kv, ko = jax.random.split(key, 4)
        mk = lambda k, shape: init_weights(k, shape, self.weight_init,
                                           shape[0], shape[1],
                                           c.dist_mean, c.dist_std,
                                           dist=c.dist)
        return {
            "Wq": mk(kq, (c.n_in, c.n_out)),
            "Wk": mk(kk, (c.n_in, c.n_out)),
            "Wv": mk(kv, (c.n_in, c.n_out)),
            "Wo": mk(ko, (c.n_out, c.n_out)),
            "bo": jnp.zeros((c.n_out,), jnp.float32),
        }

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        if x.ndim != 3:
            raise ValueError(
                f"AttentionLayer needs [batch, time, features] input, got "
                f"shape {x.shape}. Stepwise rnn_time_step inference is not "
                f"supported for attention (no KV cache) — feed full windows.")
        x = self.maybe_dropout_input(x, train, rng)
        b, t, _ = x.shape
        h = c.num_heads
        d = c.n_out // h
        split = lambda z: z.reshape(b, t, h, d)
        q = split(x @ params["Wq"].astype(x.dtype))
        k = split(x @ params["Wk"].astype(x.dtype))
        v = split(x @ params["Wv"].astype(x.dtype))
        o = dispatch_attention(q, k, v, causal=c.causal, mask=mask)
        out = o.reshape(b, t, c.n_out) @ params["Wo"].astype(x.dtype) \
            + params["bo"].astype(x.dtype)
        if c.residual:
            if c.n_in != c.n_out:
                raise ValueError("residual attention needs n_in == n_out")
            out = out + x
        if mask is not None:
            out = out * mask[:, :, None].astype(out.dtype)
        return out, state
