"""Batch normalization + local response normalization.

Parity: ``nn/layers/normalization/BatchNormalization.java:38`` (+
``CudnnBatchNormalizationHelper.java``) and
``LocalResponseNormalization.java`` (+ cuDNN LRN helper). On TPU both
are plain fused XLA elementwise/reduce graphs; the moving statistics are
non-trainable state threaded through the compiled train step (the
reference mutated layer fields).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.nn.conf import layers as L
from deeplearning4j_tpu.nn.layers.base import LayerImpl, register_impl


@register_impl(L.BatchNormalization)
class BatchNormalizationImpl(LayerImpl):
    """Normalizes over batch (FF [b,f]) or batch+space (CNN NHWC
    [b,h,w,c], per channel)."""

    batch_statistics = True  # train-mode moments span the batch: padded
    # rows would pollute them, so tail-batch padding is gated off

    def init_params(self, key) -> Dict[str, jnp.ndarray]:
        c = self.conf
        n = c.n_out
        if c.lock_gamma_beta:
            return {}
        return {"gamma": jnp.full((n,), c.gamma, jnp.float32),
                "beta": jnp.full((n,), c.beta, jnp.float32)}

    def init_state(self):
        n = self.conf.n_out
        return {"mean": jnp.zeros((n,), jnp.float32),
                "var": jnp.ones((n,), jnp.float32)}

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        axes = tuple(range(x.ndim - 1))  # (0,) ff / (0,1,2) nhwc
        if train:
            # One-pass moments in f32: E[x] and E[x²] reduce the SAME
            # input, so XLA sibling-fuses them into a single HBM read of
            # the activation (jnp.var's (x-mean)² form forces a second
            # full pass — measured ~5ms/step on ResNet-50/v5e).
            xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
            mean = jnp.mean(xf, axis=axes)
            var = jnp.maximum(jnp.mean(jnp.square(xf), axis=axes)
                              - jnp.square(mean), 0.0)
            d = jnp.asarray(c.decay, jnp.float32)
            new_state = {
                "mean": d * state["mean"] + (1 - d) * mean,
                "var": d * state["var"] + (1 - d) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        # Fold the whole normalize into one per-element FMA with [c]
        # vectors: scale = γ/√(var+ε), shift = β − mean·scale. The [c]
        # math stays f32; only the wide op runs in compute dtype.
        inv = jax.lax.rsqrt(var + c.eps)
        if c.lock_gamma_beta:
            scale = c.gamma * inv
            shift = c.beta - mean * scale
        else:
            scale = params["gamma"] * inv
            shift = params["beta"] - mean * scale
        out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
        return out, new_state

    def regularization_penalty(self, params):
        return jnp.asarray(0.0, jnp.float32)  # reference: no l1/l2 on BN params


@register_impl(L.LocalResponseNormalization)
class LocalResponseNormalizationImpl(LayerImpl):
    """Cross-channel LRN on NHWC: y = x / (k + alpha*Σ_window x²)^beta,
    window of ``n`` adjacent channels (``LocalResponseNormalization.java``).
    Implemented as a channel-axis reduce_window — one fused XLA op."""

    def forward(self, params, x, state, train, rng=None, mask=None):
        c = self.conf
        n = int(c.n)
        half = n // 2
        sq = x * x
        s = jax.lax.reduce_window(
            sq, 0.0, jax.lax.add,
            window_dimensions=(1, 1, 1, n),
            window_strides=(1, 1, 1, 1),
            # asymmetric for even n so output channels == input channels
            padding=((0, 0), (0, 0), (0, 0), (half, n - 1 - half)),
        )
        return x / jnp.power(c.k + c.alpha * s, c.beta), state
