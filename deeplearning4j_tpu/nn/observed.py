"""Lazy observer synchronization for model state attributes.

``ParallelWrapper`` in averaging mode keeps the real training state on a
leading worker axis; observers (hooks/listeners — the reference's
``IterationListener`` chain, ``optimize/api/IterationListener.java``)
must nevertheless see the CURRENT worker-mean model when they read
``model.params`` / ``opt_state`` / ``states``. Materializing that mean
every step purely in case someone looks is measurable overhead when
``averaging_frequency > 1`` on large models, so the wrapper instead
installs a pending-sync thunk and these descriptors run it on first
read — observers that only consume the score never pay for the mean.
"""

from __future__ import annotations

import threading

# Per-instance locks for pending-sync handoffs (ADVICE r3/r4): reads
# can come from non-training threads (a UiServer polling model.params
# while ParallelWrapper.fit runs), and the get-and-clear below must not
# let two readers both run the thunk, nor let the training thread
# donate the buffers a reader's thunk is still consuming. The lock is
# per model instance so a slow thunk on one model never blocks reads on
# another, and a thunk that reads a *different* object's synced attrs
# cannot self-deadlock on a shared non-reentrant lock. _LOCK_CREATION
# only guards first-touch creation of an instance lock.
#
# Constraint on thunk authors: a thunk may READ another object's synced
# attrs only if those reads form no cycle (a's thunk reading b.params
# while b's thunk reads a.params is an ABBA deadlock). In-repo thunks
# only write through the descriptors (writes take no lock), and
# ParallelWrapper installs at most a one-way read, so the constraint is
# about custom observers.
_LOCK_CREATION = threading.Lock()


def _sync_lock(obj) -> threading.Lock:
    lock = obj.__dict__.get("_observer_sync_lock")
    if lock is None:
        with _LOCK_CREATION:
            lock = obj.__dict__.setdefault("_observer_sync_lock",
                                           threading.Lock())
    return lock


def clear_pending_sync(obj) -> None:
    """Drop ``obj``'s pending observer sync. Blocks while a reader
    thread is mid-thunk, so the caller may safely donate the buffers
    the thunk references once this returns."""
    with _sync_lock(obj):
        obj.__dict__["_observer_sync"] = None


class SyncedStateAttr:
    """Data descriptor backing ``params``/``opt_state``/``states``.

    Reads run (and clear) the instance's pending ``_observer_sync``
    thunk first, so an externally-installed refresh happens exactly
    once, and only if somebody actually looks. Writes go straight to
    the backing slot (the thunk itself writes through here while
    already cleared, so there is no recursion).

    ``invalidates`` names an instance-dict key popped on every write —
    the containers declare ``opt_state`` with
    ``invalidates="_host_step_mirror"`` so any assignment (a train step,
    a checkpoint restore, ``fit_scan``) drops the host-side step mirror
    and the next fit re-resolves it from the device exactly once
    (optimize/deferred.py host_step).
    """

    def __init__(self, name: str, invalidates: str = None):
        self._slot = "_synced_" + name
        self._invalidates = invalidates

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        if obj.__dict__.get("_observer_sync") is not None:  # cheap probe
            with _sync_lock(obj):  # atomic get-and-clear + run (ADVICE r3)
                sync = obj.__dict__.get("_observer_sync")
                if sync is not None:
                    obj.__dict__["_observer_sync"] = None
                    sync()
        return obj.__dict__.get(self._slot)

    def __set__(self, obj, value):
        if self._invalidates is not None:
            obj.__dict__.pop(self._invalidates, None)
        obj.__dict__[self._slot] = value

    def __delete__(self, obj):
        obj.__dict__.pop(self._slot, None)
