"""Loss functions with per-example masking.

Parity surface: DL4J 0.6.1 ``LossFunctions.LossFunction`` (used by output
layers, ``nn/conf/layers/OutputLayer`` + ND4J ``LossCalculation``). All
losses here:

- take pre-activation outputs OR activated outputs? → activated outputs
  ("labels" vs "predictions"), matching the reference where the output
  layer activates then scores; the fused softmax+NLL fast path is applied
  automatically for MCXENT/NEGATIVELOGLIKELIHOOD when given logits via
  ``from_logits=True`` (numerically the TPU-correct formulation),
- support an optional per-example (or per-timestep) mask, the reference's
  variable-length time-series machinery (``TimeSeriesUtils.java``),
- reduce to *mean over examples* of the *sum over output features*, the
  reference's score convention (score = loss / #examples).
"""

from __future__ import annotations

import enum
from typing import Optional, Union

import jax
import jax.numpy as jnp

_EPS = 1e-7


def _masked_mean(per_ex: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    """Masked mean whose value AND gradients are bitwise-identical to
    ``jnp.mean`` over the unmasked rows (the shape-bucketing tail-batch
    parity guarantee):

    - gradients flow through the true division ``total / count`` —
      its cotangent ``g / count`` is the same correctly-rounded value
      as the constant-folded ``g * (1/n)`` the mean backward emits;
    - the FORWARD value is corrected to ``total * (1/count)``, the
      rounding XLA's strength-reduced division-by-compile-time-count
      produces for ``jnp.mean`` (one extra rounding vs true division
      when the count is not a power of two). The correction rides a
      ``stop_gradient`` so the backward graph is exactly the division
      form; ``d + stop_grad(r - d) == r`` exactly (Sterbenz: r, d are
      within one ulp, so ``r - d`` and the re-add are exact)."""
    mask = mask.astype(per_ex.dtype)
    total = jnp.sum(per_ex * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    d = total / count
    r = total * (1.0 / count)
    return d + jax.lax.stop_gradient(r - d)


class LossFunction(str, enum.Enum):
    MSE = "mse"
    L1 = "l1"
    L2 = "l2"
    XENT = "xent"  # binary cross-entropy
    MCXENT = "mcxent"  # multi-class cross-entropy
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"  # == MCXENT in the reference
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    SQUARED_HINGE = "squared_hinge"
    KL_DIVERGENCE = "kl_divergence"
    MEAN_ABSOLUTE_ERROR = "mean_absolute_error"
    MEAN_ABSOLUTE_PERCENTAGE_ERROR = "mean_absolute_percentage_error"
    MEAN_SQUARED_LOGARITHMIC_ERROR = "mean_squared_logarithmic_error"
    POISSON = "poisson"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"


def _per_example(loss_fn_name: LossFunction, labels: jnp.ndarray, preds: jnp.ndarray) -> jnp.ndarray:
    """Per-example loss: sum over the feature axis (last). Shapes [..., nOut] -> [...]."""
    f = loss_fn_name
    if f in (LossFunction.MSE, LossFunction.L2):
        # DL4J scores MSE as the sum of squared errors over the feature axis
        d = labels - preds
        return jnp.sum(d * d, axis=-1)
    if f in (LossFunction.L1, LossFunction.MEAN_ABSOLUTE_ERROR):
        return jnp.sum(jnp.abs(labels - preds), axis=-1)
    if f in (LossFunction.XENT, LossFunction.RECONSTRUCTION_CROSSENTROPY):
        p = jnp.clip(preds, _EPS, 1.0 - _EPS)
        return -jnp.sum(labels * jnp.log(p) + (1.0 - labels) * jnp.log1p(-p), axis=-1)
    if f in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        p = jnp.clip(preds, _EPS, 1.0)
        return -jnp.sum(labels * jnp.log(p), axis=-1)
    if f is LossFunction.COSINE_PROXIMITY:
        ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
        pn = preds / (jnp.linalg.norm(preds, axis=-1, keepdims=True) + _EPS)
        return -jnp.sum(ln * pn, axis=-1)
    if f is LossFunction.HINGE:
        # labels in {-1, +1} (or one-hot converted upstream)
        return jnp.sum(jax.nn.relu(1.0 - labels * preds), axis=-1)
    if f is LossFunction.SQUARED_HINGE:
        h = jax.nn.relu(1.0 - labels * preds)
        return jnp.sum(h * h, axis=-1)
    if f is LossFunction.KL_DIVERGENCE:
        l = jnp.clip(labels, _EPS, 1.0)
        p = jnp.clip(preds, _EPS, 1.0)
        return jnp.sum(l * (jnp.log(l) - jnp.log(p)), axis=-1)
    if f is LossFunction.MEAN_ABSOLUTE_PERCENTAGE_ERROR:
        # sign-preserving clamp of the denominator (zero labels treated as +eps)
        denom = jnp.where(labels >= 0, 1.0, -1.0) * jnp.maximum(jnp.abs(labels), _EPS)
        return jnp.sum(jnp.abs((labels - preds) / denom), axis=-1) * 100.0
    if f is LossFunction.MEAN_SQUARED_LOGARITHMIC_ERROR:
        d = jnp.log1p(jnp.maximum(preds, -1.0 + _EPS)) - jnp.log1p(jnp.maximum(labels, -1.0 + _EPS))
        return jnp.sum(d * d, axis=-1)
    if f is LossFunction.POISSON:
        p = jnp.clip(preds, _EPS, None)
        return jnp.sum(p - labels * jnp.log(p), axis=-1)
    raise ValueError(f"unknown loss function {f}")


def compute_loss(
    name: Union[str, LossFunction],
    labels: jnp.ndarray,
    predictions: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    from_logits: bool = False,
    reduction: str = "mean",
) -> jnp.ndarray:
    """Masked mean-over-examples loss (scalar).

    ``labels``/``predictions``: [batch, nOut] or [batch, T, nOut] (RNN,
    reference reshapes [b,nOut,T]→[b*T,nOut]; we keep time as a leading
    structure and mask instead). ``mask`` broadcasts over the feature axis:
    [batch] or [batch, T].

    ``from_logits=True`` uses the fused log-softmax formulation for
    MCXENT/NLL and sigmoid-BCE-with-logits for XENT — numerically stable
    and what XLA fuses best; gradient-check tests verify it matches the
    activate-then-score reference semantics.

    Reduction semantics for [b, T, nOut] sequences: the default
    ``reduction="mean"`` averages over all b*T timesteps (or the mask
    count), which keeps the score scale independent of sequence length.
    The reference (``BaseOutputLayer.computeScore``) instead divides the
    summed sequence loss by minibatch size b only, so its RNN scores and
    effective learning rates scale with T; pass ``reduction="batch"`` to
    reproduce that behavior when matching reference configs exactly.
    """
    f = LossFunction(name)
    sparse = labels.ndim == predictions.ndim - 1
    if sparse and f not in (LossFunction.MCXENT,
                            LossFunction.NEGATIVELOGLIKELIHOOD):
        raise ValueError(
            f"sparse integer labels (shape {labels.shape} vs predictions "
            f"{predictions.shape}) are only supported for mcxent/nll")
    if sparse:
        # integer class-id labels: gather the target log-prob instead of
        # materializing one-hots — for a [b, t] LM batch over vocab V
        # this removes the [b, t, V] label tensor entirely (HBM traffic
        # and host->device staging shrink by a factor of V).
        # Contract: ids must be in [0, V); NEGATIVE ids are the
        # ignore-index convention — zero loss, excluded from the mean.
        # (ids >= V clamp silently under jit, unlike the one-hot path —
        # data validation belongs host-side.)
        ids = labels.astype(jnp.int32)
        ignore = ids < 0
        # flatten to 2D before the gather: XLA compiles take_along_axis
        # on a >2D operand into a catastrophic gather (measured 53 ms vs
        # 6.8 ms flattened for a [16,1024,8192] LM batch on v5e — it was
        # ~50% of the whole GPT-base train step)
        lead = ids.shape
        nout = predictions.shape[-1]
        pred2 = predictions.reshape(-1, nout)
        ids2 = jnp.clip(ids, 0, None).reshape(-1, 1)
        if from_logits:
            # -log_softmax[target] == logsumexp - target logit; gathering
            # from the RAW logits keeps the softmax out of the gather's
            # fusion entirely
            tgt = jnp.take_along_axis(pred2, ids2, axis=1)[:, 0]
            per_ex = (jax.scipy.special.logsumexp(pred2, axis=-1)
                      - tgt).reshape(lead)
        else:
            # gather first, then log N elements (not the [N, V] matrix)
            tgt = jnp.take_along_axis(pred2, ids2, axis=1)[:, 0]
            per_ex = -jnp.log(jnp.clip(tgt, _EPS, 1.0)).reshape(lead)
        if mask is None:
            mask = (~ignore).astype(per_ex.dtype)
        else:
            mask = mask.astype(per_ex.dtype) * (~ignore).astype(per_ex.dtype)
    elif from_logits and f in (LossFunction.MCXENT, LossFunction.NEGATIVELOGLIKELIHOOD):
        logp = jax.nn.log_softmax(predictions, axis=-1)
        per_ex = -jnp.sum(labels * logp, axis=-1)
    elif from_logits and f is LossFunction.XENT:
        z, y = predictions, labels
        per_ex = jnp.sum(jax.nn.relu(z) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z))), axis=-1)
    else:
        per_ex = _per_example(f, labels, predictions)

    if reduction == "batch":
        # reference semantics: sum everything, divide by minibatch size
        batch = per_ex.shape[0]
        if mask is not None:
            per_ex = per_ex * mask.astype(per_ex.dtype)
        return jnp.sum(per_ex) / batch
    if reduction != "mean":
        raise ValueError(f"unknown reduction {reduction!r} (use 'mean' or 'batch')")
    if mask is not None:
        return _masked_mean(per_ex, mask)
    return jnp.mean(per_ex)
