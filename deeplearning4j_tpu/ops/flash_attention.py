"""Flash attention as a Pallas TPU kernel.

The reference has no attention at all (SURVEY.md §2.6 — it predates
it); this is the build-plan extension (§7.7) the long-context stack
rides on, and the framework's custom-kernel slot: where the reference
dropped to cuDNN helpers (``CudnnConvolutionHelper.java:51``) for its
hot ops, the TPU build drops to Pallas for its hottest op.

Design (the standard online-softmax blocking, fitted to the MXU/VMEM):

- grid = (batch*heads, q_blocks, k_blocks); the k axis is the innermost
  ("arbitrary") dimension so the [block_q, d] accumulator, running max
  and running denominator live in VMEM scratch across k steps — the
  O(t²) score matrix never exists in HBM, which is the whole point:
  attention becomes compute-bound on the MXU instead of HBM-bound.
- both matmuls (q·kᵀ and p·v) run on the MXU in f32 accumulation
  (``preferred_element_type``) regardless of the bf16 input dtype.
- causal masking prunes: k-blocks entirely above the diagonal are
  skipped under ``@pl.when`` (no MXU work), the diagonal block is
  masked with a broadcasted iota.
- backward: ``jax.custom_vjp`` with recompute — the forward saves only
  (q, k, v) and the backward differentiates the XLA reference
  implementation (``ops/attention.py``), i.e. flash-forward +
  rematerialized-backward. Training still never stores the forward's
  O(t²) weights; the backward builds them blockwise under XLA fusion.

CPU processes (the test mesh) run the same kernel under the Pallas
interpreter, so the kernel is exercised everywhere; the TPU path
compiles via Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpreter needs only pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.ops.attention import scaled_dot_product_attention

_NEG_INF = -1e30  # finite sentinel: -inf scratch + exp() is nan-prone in bf16


def _pick_block(t: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and t % b == 0:
            return b
    return 0


def _kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref,
            *, scale: float, causal: bool, block_q: int, block_k: int,
            offset: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # causal: query global row r attends keys <= r + offset
    # (offset = tk - tq, matching ops/attention.py tril(k=tk-tq)).
    # A k-block whose first column exceeds the q-block's last allowed
    # key is dead weight — skip its MXU work entirely.
    q_last = qi * block_q + block_q - 1 + offset
    live = (not causal) or (kj * block_k <= q_last)

    @pl.when(live)
    def _step():
        # keep native (bf16) inputs on the MXU — f32 accumulation comes
        # from preferred_element_type; upcasting first would halve MXU
        # throughput
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            ok = (qi * block_q + rows + offset) >= (kj * block_k + cols)
            s = jnp.where(ok, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(kj == nk - 1)
    def _final():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _flash_fwd_impl(q, k, v, causal: bool, block_q: int, block_k: int,
                    interpret: bool):
    """q,k,v: [bh, t, d] (heads folded into batch)."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    nq, nk = tq // block_q, tk // block_k
    kernel = functools.partial(
        _kernel, scale=scale, causal=causal, block_q=block_q,
        block_k=block_k, offset=tk - tq)
    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        scratch = [pltpu.VMEM((block_q, d), jnp.float32),
                   pltpu.VMEM((block_q, 128), jnp.float32),
                   pltpu.VMEM((block_q, 128), jnp.float32)]
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))
    else:  # interpreter path (CPU test meshes)
        vmem = {}
        scratch = [pltpu.VMEM((block_q, d), jnp.float32) if _HAS_PLTPU
                   else jax.ShapeDtypeStruct((block_q, d), jnp.float32),
                   pltpu.VMEM((block_q, 128), jnp.float32) if _HAS_PLTPU
                   else jax.ShapeDtypeStruct((block_q, 128), jnp.float32),
                   pltpu.VMEM((block_q, 128), jnp.float32) if _HAS_PLTPU
                   else jax.ShapeDtypeStruct((block_q, 128), jnp.float32)]
        params = dict(interpret=True)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=scratch,
        **params,
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    return _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret), (q, k, v)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    # rematerialized backward through the XLA reference formulation
    # ([bh, t, d] -> [bh, t, 1, d] single-head call)
    q, k, v = res

    def ref(q, k, v):
        return scaled_dot_product_attention(
            q[:, :, None, :], k[:, :, None, :], v[:, :, None, :],
            causal=causal)[:, :, 0, :]

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [b, tq, h, d]
    k: jnp.ndarray,  # [b, tk, h, d]
    v: jnp.ndarray,  # [b, tk, h, d]
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
    block_q: int = 512,
    block_k: int = 1024,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Drop-in for ``scaled_dot_product_attention`` (same [b, t, h, d]
    convention). Falls back to the XLA formulation when the kernel
    can't apply (key-validity mask, or sequence lengths that no block
    size divides) — numerics match either way (tested).

    Block defaults were tuned on v5e (bq=512/bk=1024: matches XLA at
    4k, 1.5x faster at 16k, and runs 32k-causal where the XLA
    formulation OOMs on the [b,h,t,t] score buffer)."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    if mask is not None or not bq or not bk:
        return scaled_dot_product_attention(q, k, v, causal=causal, mask=mask)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fold = lambda z: z.transpose(0, 2, 1, 3).reshape(b * h, z.shape[1], d)
    o = _flash(fold(q), fold(k), fold(v), causal, bq, bk, interpret)
    return o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
