"""Flash attention as Pallas TPU kernels — forward AND backward.

The reference has no attention at all (SURVEY.md §2.6 — it predates
it); this is the build-plan extension (§7.7) the long-context stack
rides on, and the framework's custom-kernel slot: where the reference
dropped to cuDNN helpers (``CudnnConvolutionHelper.java:51``) for its
hot ops, the TPU build drops to Pallas for its hottest op.

Design (online-softmax blocking fitted to the MXU/VMEM):

- forward grid = (batch*heads, q_blocks, k_blocks); the k axis is the
  innermost ("arbitrary") dimension so the [block_q, d] accumulator,
  running max and running denominator live in VMEM scratch across k
  steps — the O(t²) score matrix never exists in HBM, which is the
  whole point: attention becomes compute-bound on the MXU instead of
  HBM-bound. The forward also emits the per-row logsumexp ``lse`` so
  the backward never has to replay the online softmax.
- causal masking: k-blocks entirely above the diagonal are skipped
  under ``@pl.when`` (no MXU/DMA compute); live blocks all apply the
  iota mask — a masked/unmasked branch split was measured ~2x SLOWER
  per step (duplicated conditional bodies defeat Mosaic's pipelining),
  so one masked body wins.
- the softmax scale is folded into q ONCE in XLA before the kernel
  (a per-step in-kernel multiply over [block_q, d] measured ~6x more
  expensive than the single pre-pass at 16k).
- backward = two more Pallas kernels (the TPU shape of the standard
  two-pass flash backward): a dq kernel (k innermost, dq accumulator
  in VMEM) and a dk/dv kernel (q innermost, dk+dv accumulators in
  VMEM). Both compute the score block TRANSPOSED ([block_k, block_q])
  so the per-query ``lse`` and ``delta = rowsum(dO·O)`` vectors enter
  as [1, block_q] row broadcasts — no per-step relayouts. The O(t²)
  weights are rebuilt blockwise from (q, k, lse) and never touch HBM,
  so a 32k-causal TRAINING step fits where the XLA formulation OOMs
  on the [b, h, t, t] score buffer.
- all matmuls run on the MXU in f32 accumulation
  (``preferred_element_type``) from native-bf16 operands.
- the forward is VPU-bound at ~32% MFU (16k causal, v5e) — a measured
  plateau, not a tuning gap: per k-step the online-softmax chain
  (~10M VPU elementwise ops) hides the 2 MXU matmuls. Rejected
  variants (r4, all measured on-chip): triangular live-block grid,
  scalar-prefetch index tables, precomputed D-matrix masks (f32 slow,
  i8 unsupported), masked/unmasked branch split, dead-block index
  clamping, exp2-space softmax, 2048-wide blocks (VMEM). See
  BASELINE.md "Flash-attention forward roofline". The backward's
  higher MFU is structural (7 matmuls per 2 exp chains).

CPU processes (the test mesh) run the same kernels under the Pallas
interpreter, so fwd+bwd are exercised everywhere; the TPU path
compiles via Mosaic.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some non-TPU builds; interpreter needs only pl
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False

from deeplearning4j_tpu.ops.attention import scaled_dot_product_attention

_NEG_INF = -1e30  # finite sentinel: -inf scratch + exp() is nan-prone in bf16


def _pick_block(t: int, preferred: int) -> int:
    for b in (preferred, 512, 256, 128, 64, 32, 16, 8):
        if b <= preferred and t % b == 0:
            return b
    return 0


def _scratch(shape):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, jnp.float32)
    return jax.ShapeDtypeStruct(shape, jnp.float32)


def _causal_live(offset, q0, bq, k0):
    """Whether block [q0:q0+bq) x [k0:...) intersects the causal
    triangle at all (key col c is visible to query row r iff
    r + offset >= c); dead blocks skip all compute under pl.when."""
    return k0 <= q0 + bq - 1 + offset


# --------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
                *, causal: bool, block_q: int, block_k: int, offset: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = _causal_live(offset, qi * block_q, block_q,
                        kj * block_k) if causal else True

    def _step():
        # q arrives pre-scaled (one XLA pass outside the kernel beats a
        # per-step in-kernel multiply ~6x at 16k); operands stay bf16
        # for the MXU — f32 accumulation via preferred_element_type
        s = jax.lax.dot_general(q_ref[0], k_ref[0], (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if causal:
            # one branch body, masked always: duplicating the body under
            # masked/unmasked pl.when branches measured ~2x SLOWER per
            # step than the mask passes it saves (Mosaic pipelining)
            rows = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            cols = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            ok = (qi * block_q + rows + offset) >= (kj * block_k + cols)
            s = jnp.where(ok, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        l_new = corr * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = acc_ref[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # lane-0 stores: broadcasting m/l across all 128 scratch lanes
        # measured +0.86us/step of pure VPU store traffic
        m_ref[:, :1] = m_new
        l_ref[:, :1] = l_new

    if causal:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(kj == nk - 1)
    def _final():
        denom = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)
        lse_ref[0] = m_ref[:, :1] + jnp.log(denom)   # [block_q, 1] column


def _flash_fwd_impl(q, k, v, causal: bool, block_q: int, block_k: int,
                    interpret: bool):
    """q,k,v: [bh, t, d] (heads folded into batch) -> (o, lse[bh, t])."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    q = (q * (1.0 / d ** 0.5)).astype(q.dtype)  # fold softmax scale once
    nq, nk = tq // block_q, tk // block_k
    kernel = functools.partial(
        _fwd_kernel, causal=causal, block_q=block_q,
        block_k=block_k, offset=tk - tq)
    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))
    else:  # interpreter path (CPU test meshes)
        vmem = {}
        params = dict(interpret=True)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0), **vmem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            _scratch((block_q, d)),
            _scratch((block_q, 128)),
            _scratch((block_q, 128)),
        ],
        **params,
    )(q, k, v)


# -------------------------------------------------------------- backward
#
# Both kernels build the TRANSPOSED score block sT = (q·scale)·kᵀ as
# [block_k, block_q] so lse/delta broadcast as [1, block_q] rows.
# pT = exp(sT - lse); dPT = v·dOᵀ; dsT = pT ∘ (dPT - delta).
#   dv += pTᵀ... no: dv = Σ_i P_ij dO_i  => dv_acc += pT · dO
#   dk = Σ_i dS_ij (q_i·scale)           => dk_acc += dsT · qs
#   dq = scale · Σ_j dS_ij k_j           => dq_acc += dsTᵀ · k (contract 0,0)

def _bwd_block(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
               *, masked, q0, k0, offset, block_q, block_k):
    qs = q_ref[0]  # pre-scaled outside the kernels
    sT = jax.lax.dot_general(k_ref[0], qs, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    if masked:
        krow = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 0)
        qcol = jax.lax.broadcasted_iota(jnp.int32, (block_k, block_q), 1)
        ok = (q0 + qcol + offset) >= (k0 + krow)
        sT = jnp.where(ok, sT, _NEG_INF)
    # lse/delta arrive as [1, block_q] rows (pre-reshaped outside the
    # kernel) and broadcast across the block_k sublanes
    pT = jnp.exp(sT - lse_ref[0])                    # [block_k, block_q]
    dPT = jax.lax.dot_general(v_ref[0], do_ref[0], (((1,), (1,)), ((), ())),
                              preferred_element_type=jnp.float32)
    dsT = pT * (dPT - dlt_ref[0])
    return qs, pT.astype(v_ref.dtype), dsT.astype(q_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
               acc_ref, *, scale, causal, block_q, block_k, offset):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    live = _causal_live(offset, qi * block_q, block_q,
                        kj * block_k) if causal else True

    def _step():
        _, _, dsT = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
            masked=causal, q0=qi * block_q, k0=kj * block_k, offset=offset,
            block_q=block_q, block_k=block_k)
        acc_ref[:] += jax.lax.dot_general(
            dsT, k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(kj == nk - 1)
    def _final():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, dlt_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, causal, block_q, block_k, offset):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = _causal_live(offset, qi * block_q, block_q,
                        kj * block_k) if causal else True

    def _step():
        qs, pT, dsT = _bwd_block(
            q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
            masked=causal, q0=qi * block_q, k0=kj * block_k, offset=offset,
            block_q=block_q, block_k=block_k)
        dv_acc[:] += jax.lax.dot_general(
            pT, do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dk_acc[:] += jax.lax.dot_general(
            dsT, qs, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(qi == nq - 1)
    def _final():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _flash_bwd_impl(q, k, v, o, lse, g, causal: bool,
                    block_q: int, block_k: int, interpret: bool):
    bh, tq, d = q.shape
    tk = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    q = (q * scale).astype(q.dtype)  # pre-scale once; dq re-scales at the end
    nq, nk = tq // block_q, tk // block_k
    offset = tk - tq
    # delta = rowsum(dO ∘ O): one fused XLA pass; reshape lse/delta to
    # [bh, 1, tq] rows (free: tq stays contiguous) so the kernels
    # consume them as lane-major broadcasts without relayouts
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, 1, tq)
    lse = lse.reshape(bh, 1, tq)

    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")))
    else:
        vmem = {}
        params = dict(interpret=True)

    qspec = pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0), **vmem)
    kspec = pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0), **vmem)
    rowspec = pl.BlockSpec((1, 1, block_q), lambda b, i, j: (b, 0, i), **vmem)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, rowspec, rowspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[_scratch((block_q, d))],
        **params,
    )(q, k, v, g, lse, delta)

    # dk/dv grid: (bh, k_blocks, q_blocks) — q innermost
    kspec2 = pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0), **vmem)
    qspec2 = pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0), **vmem)
    rowspec2 = pl.BlockSpec((1, 1, block_q), lambda b, j, i: (b, 0, i), **vmem)
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal,
                          block_q=block_q, block_k=block_k, offset=offset),
        grid=(bh, nk, nq),
        in_specs=[kspec2, kspec2, qspec2, qspec2, rowspec2, rowspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, tk, d), v.dtype)],
        scratch_shapes=[_scratch((block_k, d)),
                        _scratch((block_k, d))],
        **params,
    )(k, v, q, g, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    # backward blocks: score blocks live in VMEM 4x over (pT/dPT/dsT
    # temporaries), so cap at 512x512. A caller-chosen forward block
    # > 512 whose length has no <=512 divisor in the candidate list
    # would make _pick_block return 0 — fall back to the forward block
    # (it ran, so it divides the length) rather than divide by zero.
    bq = _pick_block(q.shape[1], min(block_q, 512)) or block_q
    bk = _pick_block(k.shape[1], min(block_k, 512)) or block_k
    return _flash_bwd_impl(q, k, v, o, lse, g, causal, bq, bk, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jnp.ndarray,  # [b, tq, h, d]
    k: jnp.ndarray,  # [b, tk, h, d]
    v: jnp.ndarray,  # [b, tk, h, d]
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Drop-in for ``scaled_dot_product_attention`` (same [b, t, h, d]
    convention). Falls back to the XLA formulation when the kernel
    can't apply (key-validity mask, sequence lengths that no block
    size divides, or causal cross-attention with tq > tk — whose
    zero-attendable-key rows the online softmax would silently average
    over V instead of matching the oracle) — numerics match either way
    (tested).

    Both forward AND backward are Pallas kernels: training never
    materializes the O(t²) score matrix, so 32k-causal train steps fit
    where the XLA formulation OOMs on the [b, h, t, t] buffer."""
    b, tq, h, d = q.shape
    tk = k.shape[1]
    # v5e-tuned defaults: causal favors square 1024-blocks (fewer
    # diagonal crossings per live block); non-causal favors 512x1024
    if block_q is None:
        block_q = 1024 if causal else 512
    if block_k is None:
        block_k = 1024
    bq = _pick_block(tq, block_q)
    bk = _pick_block(tk, block_k)
    if mask is not None or not bq or not bk or (causal and tq > tk):
        return scaled_dot_product_attention(q, k, v, causal=causal, mask=mask)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    fold = lambda z: z.transpose(0, 2, 1, 3).reshape(b * h, z.shape[1], d)
    o = _flash(fold(q), fold(k), fold(v), causal, bq, bk, interpret)
    return o.reshape(b, h, tq, d).transpose(0, 2, 1, 3)
