"""Fused LSTM-scan Pallas TPU kernel — the INFERENCE fast path.

The second custom-kernel slot (after ``ops/flash_attention.py``): the
BASELINE.json "CudnnLSTMHelper → XLA while-loop" north star, taken one
step further for the forward pass. Measured on v5e at the char-RNN
bench shape (b1024/n512/t128, bf16):

- forward: XLA ``lax.scan`` 25.2 ms → this kernel 17.1 ms (-32%) —
  the recurrent gemm and the gate nonlinearities fuse in VMEM, with
  the [n, 4n] recurrent weight and the (h, c) carries resident in
  scratch across every timestep (grid (batch_blocks, t), t innermost
  "arbitrary"),
- training: measured and deliberately NOT routed here. XLA's fused
  scan-grad runs fwd+bwd in 31 ms; the best split alternative (this
  kernel's forward + a hand-written residual BPTT, below) measured
  44 ms — the per-step latency of a second sequential backward scan
  costs more than the forward fusion saves. ``nn/layers/recurrent``
  therefore dispatches here only on inference paths (train=False) and
  keeps the XLA scan for the train step.

The kernel IS still differentiable (custom VJP from streamed-out gate
residuals, gradient-checked against the oracle) so a future faster
backward can flip the train path without API change.

Semantics: Graves LSTM with peepholes, sigmoid gates / tanh block
(``LSTMHelpers.java:131``) — exactly ``_lstm_scan``'s math; dispatch
requires no mask, default activations, and tileable shapes. CPU test
meshes run the same kernel under the Pallas interpreter.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - absent on some non-TPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _scratch(shape, dtype=jnp.float32):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)


def _cell(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
          h_scr, c_scr, n: int):
    """ONE Graves step against the VMEM-resident carries — the shared
    body of both kernel variants (keeping the gate math in one place so
    the residual and inference paths can never desynchronize).
    Returns (i, f, o, blk, c_new, h_new) and advances the scratch."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        # h carry lives in the MXU operand dtype: a per-step f32->bf16
        # cast would relayout [b, n] before every recurrent gemm
        h_scr[:] = h0_ref[...].astype(h_scr.dtype)
        c_scr[:] = c0_ref[...].astype(jnp.float32)

    c_prev = c_scr[:]
    # recurrent gemm fused with the gate math: g = xg_t + h_prev @ Wr
    g = xg_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h_scr[:], wr_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    # Graves gate order [input, forget, output, block]; peepholes read
    # c_prev for i/f and c_new for o (LSTMHelpers.java:131)
    i = jax.nn.sigmoid(g[:, :n] + c_prev * wci_ref[0])
    f = jax.nn.sigmoid(g[:, n:2 * n] + c_prev * wcf_ref[0])
    blk = jnp.tanh(g[:, 3 * n:])
    c_new = f * c_prev + i * blk
    o = jax.nn.sigmoid(g[:, 2 * n:3 * n] + c_new * wco_ref[0])
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new.astype(h_scr.dtype)
    c_scr[:] = c_new
    return i, f, o, blk, c_new, h_new


def _fwd_kernel(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
                h_ref, i_ref, f_ref, o_ref, blk_ref, c_ref,
                h_scr, c_scr, *, n: int):
    """Training/vjp variant: streams gate residuals for the BPTT."""
    i, f, o, blk, c_new, h_new = _cell(
        xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
        h_scr, c_scr, n)
    h_ref[0] = h_new.astype(h_ref.dtype)
    i_ref[0] = i.astype(i_ref.dtype)
    f_ref[0] = f.astype(f_ref.dtype)
    o_ref[0] = o.astype(o_ref.dtype)
    blk_ref[0] = blk.astype(blk_ref.dtype)
    c_ref[0] = c_new.astype(c_ref.dtype)


def _fwd_only_kernel(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref,
                     c0_ref, h_ref, hl_ref, cl_ref, h_scr, c_scr, *, n: int):
    """Inference variant: h sequence + final carries only — no residual
    streaming (5/6 of the full variant's output bandwidth)."""
    nt = pl.num_programs(1)
    _, _, _, _, c_new, h_new = _cell(
        xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
        h_scr, c_scr, n)
    h_ref[0] = h_new.astype(h_ref.dtype)

    @pl.when(pl.program_id(1) == nt - 1)
    def _final():
        hl_ref[...] = h_new.astype(hl_ref.dtype)
        cl_ref[...] = c_new.astype(cl_ref.dtype)


def _fwd_pallas(xg, wr, wci, wcf, wco, h0, c0, block_b: int, interpret: bool,
                with_residuals: bool = True):
    """xg: [t, b, 4n] → with_residuals: (h_seq, (i, f, o, blk, c));
    else (h_seq, (h_last, c_last)) with no residual streaming."""
    t, b, g4 = xg.shape
    n = g4 // 4
    nb = b // block_b
    kernel = functools.partial(
        _fwd_kernel if with_residuals else _fwd_only_kernel, n=n)
    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")))
    else:
        vmem = {}
        params = dict(interpret=True)
    step_spec = lambda last: pl.BlockSpec((1, block_b, last),
                                          lambda i, s: (s, i, 0), **vmem)
    wr_spec = pl.BlockSpec((n, g4), lambda i, s: (0, 0), **vmem)
    row_spec = pl.BlockSpec((1, n), lambda i, s: (0, 0), **vmem)
    carry_spec = pl.BlockSpec((block_b, n), lambda i, s: (i, 0), **vmem)
    if with_residuals:
        out_specs = [step_spec(n)] * 6
        out_shape = [jax.ShapeDtypeStruct((t, b, n), xg.dtype)] * 6
    else:
        out_specs = [step_spec(n), carry_spec, carry_spec]
        out_shape = [jax.ShapeDtypeStruct((t, b, n), xg.dtype),
                     jax.ShapeDtypeStruct((b, n), xg.dtype),
                     jax.ShapeDtypeStruct((b, n), jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[step_spec(g4), wr_spec, row_spec, row_spec, row_spec,
                  carry_spec, carry_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_scratch((block_b, n), xg.dtype),
                        _scratch((block_b, n))],
        **params,
    )(xg, wr, wci.reshape(1, n), wcf.reshape(1, n), wco.reshape(1, n),
      h0, c0)
    return out[0], tuple(out[1:])


def _bwd_from_residuals(res, wr, wci, wcf, wco, h0, c0, g_hseq, g_hlast,
                        g_clast):
    """Hand-written BPTT from forward residuals.

    res: (i, f, o, blk, c) each [t, b, n]; g_hseq [t, b, n] cotangent
    of the h sequence; g_hlast/g_clast cotangents of the final carry.
    Returns (d_xg, dWr, dwci, dwcf, dwco, dh0, dc0).
    """
    i, f, o, blk, c = (r.astype(jnp.float32) for r in res)
    t, b, n = i.shape
    wr_w = wr  # bf16 gemm operand; f32 accumulation via preferred type
    c_prev = jnp.concatenate([c0.astype(jnp.float32)[None], c[:-1]], axis=0)
    tanh_c = jnp.tanh(c)
    gout = g_hseq.astype(jnp.float32).at[-1].add(
        g_hlast.astype(jnp.float32))

    def step(carry, inp):
        dh_rec, dc_carry = carry
        i_t, f_t, o_t, blk_t, c_t, cp_t, th_t, gout_t = inp
        dh = gout_t + dh_rec
        do = dh * th_t
        da_o = do * o_t * (1 - o_t)
        dc = dh * o_t * (1 - th_t * th_t) + dc_carry + da_o * wco
        dblk = dc * i_t
        da_g = dblk * (1 - blk_t * blk_t)
        di = dc * blk_t
        da_i = di * i_t * (1 - i_t)
        df = dc * cp_t
        da_f = df * f_t * (1 - f_t)
        dc_next = dc * f_t + da_i * wci + da_f * wcf
        dg = jnp.concatenate([da_i, da_f, da_o, da_g], axis=-1)  # [b, 4n]
        dh_next = jax.lax.dot_general(
            dg.astype(wr_w.dtype), wr_w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (dh_next, dc_next), dg

    zero = jnp.zeros((b, n), jnp.float32)
    (dh0, dc0), dg_seq = jax.lax.scan(
        step, (zero, g_clast.astype(jnp.float32)),
        (i, f, o, blk, c, c_prev, tanh_c, gout),
        reverse=True)
    # non-sequential reductions hoisted to full-sequence einsums;
    # h_{t-1} = o_{t-1} * tanh(c_{t-1}) with h_{-1} = h0
    h_prev = jnp.concatenate(
        [h0.astype(jnp.float32)[None], (o * tanh_c)[:-1]], axis=0)
    dwr = jnp.einsum("tbn,tbg->ng", h_prev, dg_seq,
                     preferred_element_type=jnp.float32)
    da_i, da_f, da_o = (dg_seq[..., :n], dg_seq[..., n:2 * n],
                        dg_seq[..., 2 * n:3 * n])
    dwci = jnp.sum(da_i * c_prev, axis=(0, 1))
    dwcf = jnp.sum(da_f * c_prev, axis=(0, 1))
    dwco = jnp.sum(da_o * c, axis=(0, 1))
    return dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fused(xg, wr, wci, wcf, wco, h0, c0, block_b, interpret):
    # primal (not being differentiated): the fwd-only kernel — no
    # residual streaming (5/6 less output bandwidth)
    h_seq, (h_last, c_last) = _fwd_pallas(
        xg, wr, wci, wcf, wco, h0, c0, block_b, interpret,
        with_residuals=False)
    return h_seq, h_last, c_last


def _vjp_fwd(xg, wr, wci, wcf, wco, h0, c0, block_b, interpret):
    h_seq, res = _fwd_pallas(xg, wr, wci, wcf, wco, h0, c0, block_b,
                             interpret)
    return ((h_seq, h_seq[-1], res[4][-1].astype(jnp.float32)),
            (res, wr, wci, wcf, wco, h0, c0))


def _vjp_bwd(block_b, interpret, saved, cotangents):
    res, wr, wci, wcf, wco, h0, c0 = saved
    g_hseq, g_hlast, g_clast = cotangents
    dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0 = _bwd_from_residuals(
        res, wr, wci.astype(jnp.float32), wcf.astype(jnp.float32),
        wco.astype(jnp.float32), h0, c0, g_hseq, g_hlast, g_clast)
    # cotangents must match the primal dtypes (bf16 params included)
    return (dg_seq.astype(res[0].dtype), dwr.astype(wr.dtype),
            dwci.astype(wci.dtype), dwcf.astype(wcf.dtype),
            dwco.astype(wco.dtype), dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


_fused.defvjp(_vjp_fwd, _vjp_bwd)


def _pick_block_b(b: int) -> int:
    # 256 rows max: six double-buffered per-step output blocks + the
    # xg block + resident Wr must fit the 16MB scoped-VMEM budget
    for cand in (256, 128, 64, 32, 16, 8):
        if b % cand == 0:
            return cand
    return 0


def _on_tpu() -> bool:  # patchable seam for tests
    return jax.default_backend() == "tpu"


#: largest hidden size the kernel accepts per dtype width: the
#: VMEM-resident [n, 4n] recurrent weight is 4n²·itemsize bytes and
#: must leave room for the step blocks inside the ~16MB scoped budget
_MAX_N = {2: 1024, 4: 512}


def fused_lstm_applicable(b: int, n: int, gate_act: str, block_act: str,
                          mask, itemsize: int = 2) -> bool:
    """The kernel covers the default Graves configuration on tileable
    shapes ON TPU; everything else keeps the XLA scan (on CPU/GPU hosts
    the kernel would run under the Pallas interpreter, orders of
    magnitude slower — tests exercise it by calling fused_lstm_scan
    directly). ``itemsize``: activation dtype width in bytes (bounds
    the VMEM-resident weight)."""
    return (_on_tpu()
            and mask is None and gate_act == "sigmoid"
            and block_act == "tanh"
            and n % 128 == 0 and n <= _MAX_N.get(itemsize, 512)
            and _pick_block_b(b) > 0)


def fused_lstm_scan(xg, wr, wci, wcf, wco, h0, c0
                    ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """xg [t, b, 4n] pre-projected gates → (h_seq [t, b, n], (h_T, c_T)).

    Differentiable end-to-end (custom VJP above); the final carries
    flow gradients too, so TBPTT chunk boundaries behave exactly like
    the XLA scan's.
    """
    t, b, g4 = xg.shape
    block_b = _pick_block_b(b)
    if block_b == 0:
        raise ValueError(
            f"batch {b} is not tileable (must be a multiple of 8); "
            f"gate with fused_lstm_applicable or use the XLA scan")
    interpret = jax.default_backend() != "tpu"
    h_seq, h_last, c_last = _fused(xg, wr, wci, wcf, wco, h0, c0,
                                   block_b, interpret)
    return h_seq, (h_last, c_last)
