"""Fused LSTM-scan Pallas TPU kernels — forward AND backward.

The second custom-kernel slot (after ``ops/flash_attention.py``): the
BASELINE.json "CudnnLSTMHelper → XLA while-loop" north star. Measured
on v5e at the char-RNN bench shape (b1024/n512/t128, bf16):

- forward: XLA ``lax.scan`` 25.2 ms → this kernel 17.1 ms (-32%) —
  the recurrent gemm and the gate nonlinearities fuse in VMEM, with
  the [n, 4n] recurrent weight and the (h, c) carries resident in
  scratch across every timestep (grid (batch_blocks, t), t innermost
  "arbitrary"),
- training (r5): the Pallas BPTT below takes the FULL char-RNN train
  step from 28.8% MFU (XLA fused scan-grad, the best r3/r4 result) to
  **63.5% MFU** — reverse-time grid, the dh/dc carries AND the f32
  [n, 4n] dWr accumulator resident in VMEM, gate-derivative math fused
  with both per-step gemms (dg@Wrᵀ and h_prevᵀ@dg). The r3/r4 split
  alternative (fused forward + an XLA residual-scan BPTT) measured
  21.0% — the win comes specifically from keeping the BACKWARD
  sequential loop inside one kernel too. Gradients equal the XLA scan's
  to 1e-6 in a single on-chip SGD step; ``DL4J_TPU_LSTM_TRAIN=xla``
  restores the scan path. The XLA residual BPTT (``
  _bwd_from_residuals``) remains as the n>512 / fallback backward.

Semantics: Graves LSTM with peepholes, sigmoid gates / tanh block
(``LSTMHelpers.java:131``) — exactly ``_lstm_scan``'s math; dispatch
requires no mask, default activations, and tileable shapes. CPU test
meshes run the same kernel under the Pallas interpreter.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pragma: no cover - absent on some non-TPU builds
    from jax.experimental.pallas import tpu as pltpu
    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _scratch(shape, dtype=jnp.float32):
    if _HAS_PLTPU:
        return pltpu.VMEM(shape, dtype)
    return jax.ShapeDtypeStruct(shape, dtype)


def _cell(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
          h_scr, c_scr, n: int):
    """ONE Graves step against the VMEM-resident carries — the shared
    body of both kernel variants (keeping the gate math in one place so
    the residual and inference paths can never desynchronize).
    Returns (i, f, o, blk, c_new, h_new) and advances the scratch."""
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _init():
        # h carry lives in the MXU operand dtype: a per-step f32->bf16
        # cast would relayout [b, n] before every recurrent gemm
        h_scr[:] = h0_ref[...].astype(h_scr.dtype)
        c_scr[:] = c0_ref[...].astype(jnp.float32)

    c_prev = c_scr[:]
    # recurrent gemm fused with the gate math: g = xg_t + h_prev @ Wr
    g = xg_ref[0].astype(jnp.float32) + jax.lax.dot_general(
        h_scr[:], wr_ref[...],
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
    # Graves gate order [input, forget, output, block]; peepholes read
    # c_prev for i/f and c_new for o (LSTMHelpers.java:131)
    i = jax.nn.sigmoid(g[:, :n] + c_prev * wci_ref[0])
    f = jax.nn.sigmoid(g[:, n:2 * n] + c_prev * wcf_ref[0])
    blk = jnp.tanh(g[:, 3 * n:])
    c_new = f * c_prev + i * blk
    o = jax.nn.sigmoid(g[:, 2 * n:3 * n] + c_new * wco_ref[0])
    h_new = o * jnp.tanh(c_new)
    h_scr[:] = h_new.astype(h_scr.dtype)
    c_scr[:] = c_new
    return i, f, o, blk, c_new, h_new


def _fwd_kernel(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
                h_ref, i_ref, f_ref, o_ref, blk_ref, c_ref,
                h_scr, c_scr, *, n: int):
    """Training/vjp variant: streams gate residuals for the BPTT."""
    i, f, o, blk, c_new, h_new = _cell(
        xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
        h_scr, c_scr, n)
    h_ref[0] = h_new.astype(h_ref.dtype)
    i_ref[0] = i.astype(i_ref.dtype)
    f_ref[0] = f.astype(f_ref.dtype)
    o_ref[0] = o.astype(o_ref.dtype)
    blk_ref[0] = blk.astype(blk_ref.dtype)
    c_ref[0] = c_new.astype(c_ref.dtype)


def _fwd_only_kernel(xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref,
                     c0_ref, h_ref, hl_ref, cl_ref, h_scr, c_scr, *, n: int):
    """Inference variant: h sequence + final carries only — no residual
    streaming (5/6 of the full variant's output bandwidth)."""
    nt = pl.num_programs(1)
    _, _, _, _, c_new, h_new = _cell(
        xg_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
        h_scr, c_scr, n)
    h_ref[0] = h_new.astype(h_ref.dtype)

    @pl.when(pl.program_id(1) == nt - 1)
    def _final():
        hl_ref[...] = h_new.astype(hl_ref.dtype)
        cl_ref[...] = c_new.astype(cl_ref.dtype)


def _fwd_pallas(xg, wr, wci, wcf, wco, h0, c0, block_b: int, interpret: bool,
                with_residuals: bool = True):
    """xg: [t, b, 4n] → with_residuals: (h_seq, (i, f, o, blk, c));
    else (h_seq, (h_last, c_last)) with no residual streaming."""
    t, b, g4 = xg.shape
    n = g4 // 4
    nb = b // block_b
    kernel = functools.partial(
        _fwd_kernel if with_residuals else _fwd_only_kernel, n=n)
    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")))
    else:
        vmem = {}
        params = dict(interpret=True)
    step_spec = lambda last: pl.BlockSpec((1, block_b, last),
                                          lambda i, s: (s, i, 0), **vmem)
    wr_spec = pl.BlockSpec((n, g4), lambda i, s: (0, 0), **vmem)
    row_spec = pl.BlockSpec((1, n), lambda i, s: (0, 0), **vmem)
    carry_spec = pl.BlockSpec((block_b, n), lambda i, s: (i, 0), **vmem)
    if with_residuals:
        out_specs = [step_spec(n)] * 6
        out_shape = [jax.ShapeDtypeStruct((t, b, n), xg.dtype)] * 6
    else:
        out_specs = [step_spec(n), carry_spec, carry_spec]
        out_shape = [jax.ShapeDtypeStruct((t, b, n), xg.dtype),
                     jax.ShapeDtypeStruct((b, n), xg.dtype),
                     jax.ShapeDtypeStruct((b, n), jnp.float32)]
    out = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[step_spec(g4), wr_spec, row_spec, row_spec, row_spec,
                  carry_spec, carry_spec],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[_scratch((block_b, n), xg.dtype),
                        _scratch((block_b, n))],
        **params,
    )(xg, wr, wci.reshape(1, n), wcf.reshape(1, n), wco.reshape(1, n),
      h0, c0)
    return out[0], tuple(out[1:])


def _bptt_gates(i_t, f_t, o_t, blk_t, c_prev, th, dh, dc_carry,
                wci, wcf, wco):
    """ONE reverse Graves step's gate-derivative chain — the shared
    body of the Pallas backward and the XLA residual BPTT (the _cell
    principle applied to the backward: the two paths can never
    desynchronize). All operands f32. Returns (da_i, da_f, da_o, da_g,
    dc_next)."""
    do = dh * th
    da_o = do * o_t * (1.0 - o_t)
    dc = dh * o_t * (1.0 - th * th) + dc_carry + da_o * wco
    dblk = dc * i_t
    da_g = dblk * (1.0 - blk_t * blk_t)
    di = dc * blk_t
    da_i = di * i_t * (1.0 - i_t)
    df = dc * c_prev
    da_f = df * f_t * (1.0 - f_t)
    dc_next = dc * f_t + da_i * wci + da_f * wcf
    return da_i, da_f, da_o, da_g, dc_next


def _bwd_kernel(i_ref, f_ref, o_ref, blk_ref, c_ref, cprev_ref, oprev_ref,
                gout_ref, wr_ref, wci_ref, wcf_ref, wco_ref, h0_ref, c0_ref,
                gclast_ref,
                dg_ref, dh0_ref, dc0_ref, dwr_ref, dwci_ref, dwcf_ref,
                dwco_ref,
                dh_scr, dc_scr, dwr_scr, dwci_scr, dwcf_scr, dwco_scr,
                *, n: int):
    """Fused BPTT step (reverse time): gate-derivative math + BOTH
    per-step gemms (dh recurrence dg@Wrᵀ and the dWr accumulation
    h_prevᵀ@dg) against VMEM-resident carries and a VMEM-resident
    [n, 4n] f32 dWr accumulator — the flash-bwd pattern applied to the
    LSTM scan. Grid (batch_blocks, t) with the time index map REVERSED;
    peephole/bias-free residuals (i, f, o, blk, c) stream in from the
    forward kernel, dg streams out for the (parallel, outside-kernel)
    input-projection gradients."""
    s = pl.program_id(1)
    nt = pl.num_programs(1)
    bi = pl.program_id(0)
    nb = pl.num_programs(0)

    @pl.when(s == 0)
    def _init_carries():
        dh_scr[:] = jnp.zeros_like(dh_scr)
        dc_scr[:] = gclast_ref[...].astype(jnp.float32)

    @pl.when((s == 0) & (bi == 0))
    def _init_weight_accums():
        dwr_scr[:] = jnp.zeros_like(dwr_scr)
        dwci_scr[:] = jnp.zeros_like(dwci_scr)
        dwcf_scr[:] = jnp.zeros_like(dwcf_scr)
        dwco_scr[:] = jnp.zeros_like(dwco_scr)

    f32 = jnp.float32
    i_t = i_ref[0].astype(f32)
    f_t = f_ref[0].astype(f32)
    o_t = o_ref[0].astype(f32)
    blk_t = blk_ref[0].astype(f32)
    c_t = c_ref[0].astype(f32)
    is_t0 = s == nt - 1  # reversed: the last program handles time 0
    c_prev = jnp.where(is_t0, c0_ref[...].astype(f32),
                       cprev_ref[0].astype(f32))
    th = jnp.tanh(c_t)
    dh = gout_ref[0].astype(f32) + dh_scr[:]
    da_i, da_f, da_o, da_g, dc_next = _bptt_gates(
        i_t, f_t, o_t, blk_t, c_prev, th, dh, dc_scr[:],
        wci_ref[0], wcf_ref[0], wco_ref[0])
    dc_scr[:] = dc_next
    dg = jnp.concatenate([da_i, da_f, da_o, da_g], axis=-1)  # [bb, 4n]
    dg_ref[0] = dg.astype(dg_ref.dtype)
    wdt = wr_ref.dtype
    # dh recurrence: dg @ Wrᵀ, f32 accumulation on bf16 operands
    dh_scr[:] = jax.lax.dot_general(
        dg.astype(wdt), wr_ref[...], (((1,), (1,)), ((), ())),
        preferred_element_type=f32)
    # dWr accumulation over time IN VMEM: h_prevᵀ @ dg
    h_prev = jnp.where(is_t0, h0_ref[...].astype(f32),
                       oprev_ref[0].astype(f32) * jnp.tanh(c_prev))
    dwr_scr[:] += jax.lax.dot_general(
        h_prev.astype(wdt), dg.astype(wdt), (((0,), (0,)), ((), ())),
        preferred_element_type=f32)
    dwci_scr[0] += jnp.sum(da_i * c_prev, axis=0)
    dwcf_scr[0] += jnp.sum(da_f * c_prev, axis=0)
    dwco_scr[0] += jnp.sum(da_o * c_t, axis=0)

    @pl.when(s == nt - 1)
    def _final_carries():  # this batch block's sweep is done
        dh0_ref[...] = dh_scr[:].astype(dh0_ref.dtype)
        dc0_ref[...] = dc_scr[:].astype(dc0_ref.dtype)

    @pl.when((s == nt - 1) & (bi == nb - 1))
    def _final_weights():
        dwr_ref[...] = dwr_scr[:].astype(dwr_ref.dtype)
        dwci_ref[...] = dwci_scr[:].astype(dwci_ref.dtype)
        dwcf_ref[...] = dwcf_scr[:].astype(dwcf_ref.dtype)
        dwco_ref[...] = dwco_scr[:].astype(dwco_ref.dtype)


def _bwd_pallas(res, wr, wci, wcf, wco, h0, c0, gout, g_clast,
                block_b: int, interpret: bool):
    """Reverse-time Pallas BPTT over streamed forward residuals.
    Returns (dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0) in f32 (except
    dg_seq, emitted in the residual dtype for the outer projections)."""
    i, f, o, blk, c = res
    t, b, n = i.shape
    g4 = 4 * n
    nb = b // block_b
    kernel = functools.partial(_bwd_kernel, n=n)
    if _HAS_PLTPU and not interpret:
        vmem = dict(memory_space=pltpu.VMEM)
        # BOTH dims "arbitrary": the dWr/peephole accumulators live in
        # scratch SHARED across batch blocks (init at bi==0, store at
        # bi==nb-1) — a "parallel" first dim would let a multi-core
        # Mosaic schedule split the blocks across cores and silently
        # lose contributions. (v5e is single-core; this is for v4/v5p.)
        params = dict(compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")))
    else:
        vmem = {}
        params = dict(interpret=True)
    rev = lambda last: pl.BlockSpec((1, block_b, last),
                                    lambda bi, s: (t - 1 - s, bi, 0), **vmem)
    # previous-timestep view: index t-2-s clamped at 0 (the t==0 program
    # overrides with h0/c0 in-kernel, so the clamped read is discarded)
    prev = pl.BlockSpec((1, block_b, n),
                        lambda bi, s: (jnp.maximum(t - 2 - s, 0), bi, 0),
                        **vmem)
    wr_spec = pl.BlockSpec((n, g4), lambda bi, s: (0, 0), **vmem)
    row_spec = pl.BlockSpec((1, n), lambda bi, s: (0, 0), **vmem)
    carry_spec = pl.BlockSpec((block_b, n), lambda bi, s: (bi, 0), **vmem)
    out = pl.pallas_call(
        kernel,
        grid=(nb, t),
        in_specs=[rev(n)] * 5 + [prev, prev, rev(n), wr_spec,
                                 row_spec, row_spec, row_spec,
                                 carry_spec, carry_spec, carry_spec],
        out_specs=[rev(g4), carry_spec, carry_spec, wr_spec,
                   row_spec, row_spec, row_spec],
        out_shape=[jax.ShapeDtypeStruct((t, b, g4), i.dtype),
                   jax.ShapeDtypeStruct((b, n), jnp.float32),
                   jax.ShapeDtypeStruct((b, n), jnp.float32),
                   jax.ShapeDtypeStruct((n, g4), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32),
                   jax.ShapeDtypeStruct((1, n), jnp.float32)],
        scratch_shapes=[_scratch((block_b, n)), _scratch((block_b, n)),
                        _scratch((n, g4)), _scratch((1, n)),
                        _scratch((1, n)), _scratch((1, n))],
        **params,
    )(i, f, o, blk, c, c, o, gout, wr,
      wci.reshape(1, n), wcf.reshape(1, n), wco.reshape(1, n),
      h0, c0, g_clast)
    dg_seq, dh0, dc0, dwr, dwci, dwcf, dwco = out
    return (dg_seq, dwr, dwci.reshape(n), dwcf.reshape(n),
            dwco.reshape(n), dh0, dc0)


#: VMEM budget gate for the backward kernel: the f32 [n, 4n] dWr
#: accumulator (4n²·4 bytes) + resident Wr + step blocks must fit the
#: ~16MB scoped budget — n=512 uses ~10MB, n=1024 would need 16MB for
#: the accumulator alone
_BWD_MAX_N = 512


def _bwd_from_residuals(res, wr, wci, wcf, wco, h0, c0, g_hseq, g_hlast,
                        g_clast):
    """Hand-written BPTT from forward residuals.

    res: (i, f, o, blk, c) each [t, b, n]; g_hseq [t, b, n] cotangent
    of the h sequence; g_hlast/g_clast cotangents of the final carry.
    Returns (d_xg, dWr, dwci, dwcf, dwco, dh0, dc0).
    """
    i, f, o, blk, c = (r.astype(jnp.float32) for r in res)
    t, b, n = i.shape
    wr_w = wr  # bf16 gemm operand; f32 accumulation via preferred type
    c_prev = jnp.concatenate([c0.astype(jnp.float32)[None], c[:-1]], axis=0)
    tanh_c = jnp.tanh(c)
    gout = g_hseq.astype(jnp.float32).at[-1].add(
        g_hlast.astype(jnp.float32))

    def step(carry, inp):
        dh_rec, dc_carry = carry
        i_t, f_t, o_t, blk_t, c_t, cp_t, th_t, gout_t = inp
        dh = gout_t + dh_rec
        da_i, da_f, da_o, da_g, dc_next = _bptt_gates(
            i_t, f_t, o_t, blk_t, cp_t, th_t, dh, dc_carry,
            wci, wcf, wco)
        dg = jnp.concatenate([da_i, da_f, da_o, da_g], axis=-1)  # [b, 4n]
        dh_next = jax.lax.dot_general(
            dg.astype(wr_w.dtype), wr_w, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        return (dh_next, dc_next), dg

    zero = jnp.zeros((b, n), jnp.float32)
    (dh0, dc0), dg_seq = jax.lax.scan(
        step, (zero, g_clast.astype(jnp.float32)),
        (i, f, o, blk, c, c_prev, tanh_c, gout),
        reverse=True)
    # non-sequential reductions hoisted to full-sequence einsums;
    # h_{t-1} = o_{t-1} * tanh(c_{t-1}) with h_{-1} = h0
    h_prev = jnp.concatenate(
        [h0.astype(jnp.float32)[None], (o * tanh_c)[:-1]], axis=0)
    dwr = jnp.einsum("tbn,tbg->ng", h_prev, dg_seq,
                     preferred_element_type=jnp.float32)
    da_i, da_f, da_o = (dg_seq[..., :n], dg_seq[..., n:2 * n],
                        dg_seq[..., 2 * n:3 * n])
    dwci = jnp.sum(da_i * c_prev, axis=(0, 1))
    dwcf = jnp.sum(da_f * c_prev, axis=(0, 1))
    dwco = jnp.sum(da_o * c, axis=(0, 1))
    return dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8))
def _fused(xg, wr, wci, wcf, wco, h0, c0, block_b, interpret):
    # primal (not being differentiated): the fwd-only kernel — no
    # residual streaming (5/6 less output bandwidth)
    h_seq, (h_last, c_last) = _fwd_pallas(
        xg, wr, wci, wcf, wco, h0, c0, block_b, interpret,
        with_residuals=False)
    return h_seq, h_last, c_last


def _vjp_fwd(xg, wr, wci, wcf, wco, h0, c0, block_b, interpret):
    h_seq, res = _fwd_pallas(xg, wr, wci, wcf, wco, h0, c0, block_b,
                             interpret)
    return ((h_seq, h_seq[-1], res[4][-1].astype(jnp.float32)),
            (res, wr, wci, wcf, wco, h0, c0))


def _use_pallas_bwd(t: int, b: int, n: int, block_b: int,
                    itemsize: int = 2) -> bool:
    """The fused backward applies within its VMEM budget unless
    DL4J_TPU_LSTM_BWD=xla forces the scan BPTT (A/B seam). The budget
    (_BWD_MAX_N) was measured for bf16 streams; f32 residual/gout/dg
    blocks double the footprint, so the admitted n halves with
    itemsize."""
    import os
    if os.environ.get("DL4J_TPU_LSTM_BWD", "").lower() == "xla":
        return False
    return n * itemsize <= _BWD_MAX_N * 2 and b % block_b == 0


def _vjp_bwd(block_b, interpret, saved, cotangents):
    res, wr, wci, wcf, wco, h0, c0 = saved
    g_hseq, g_hlast, g_clast = cotangents
    t, b, n = res[0].shape
    if _use_pallas_bwd(t, b, n, block_b, itemsize=res[0].dtype.itemsize):
        # fold the final-h cotangent into the sequence stream; the
        # final-c cotangent enters the kernel's dc carry directly
        gout = g_hseq.astype(jnp.float32).at[-1].add(
            g_hlast.astype(jnp.float32)).astype(res[0].dtype)
        import os
        bwd_block = min(block_b,
                        int(os.environ.get("DL4J_TPU_LSTM_BWD_BLOCK",
                                           "128")))
        if b % bwd_block != 0:  # a non-dividing sweep override would
            bwd_block = block_b  # silently truncate the batch grid
        dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0 = _bwd_pallas(
            res, wr, wci.astype(jnp.float32).reshape(1, n),
            wcf.astype(jnp.float32).reshape(1, n),
            wco.astype(jnp.float32).reshape(1, n), h0,
            c0.astype(jnp.float32),
            gout, g_clast.astype(jnp.float32),
            bwd_block, interpret)
    else:
        dg_seq, dwr, dwci, dwcf, dwco, dh0, dc0 = _bwd_from_residuals(
            res, wr, wci.astype(jnp.float32), wcf.astype(jnp.float32),
            wco.astype(jnp.float32), h0, c0, g_hseq, g_hlast, g_clast)
    # cotangents must match the primal dtypes (bf16 params included)
    return (dg_seq.astype(res[0].dtype), dwr.astype(wr.dtype),
            dwci.astype(wci.dtype), dwcf.astype(wcf.dtype),
            dwco.astype(wco.dtype), dh0.astype(h0.dtype),
            dc0.astype(c0.dtype))


_fused.defvjp(_vjp_fwd, _vjp_bwd)


def _pick_block_b(b: int) -> int:
    # 256 rows max: six double-buffered per-step output blocks + the
    # xg block + resident Wr must fit the 16MB scoped-VMEM budget
    for cand in (256, 128, 64, 32, 16, 8):
        if b % cand == 0:
            return cand
    return 0


def _on_tpu() -> bool:  # patchable seam for tests
    return jax.default_backend() == "tpu"


#: largest hidden size the kernel accepts per dtype width: the
#: VMEM-resident [n, 4n] recurrent weight is 4n²·itemsize bytes and
#: must leave room for the step blocks inside the ~16MB scoped budget
_MAX_N = {2: 1024, 4: 512}


def fused_lstm_applicable(b: int, n: int, gate_act: str, block_act: str,
                          mask, itemsize: int = 2) -> bool:
    """The kernel covers the default Graves configuration on tileable
    shapes ON TPU; everything else keeps the XLA scan (on CPU/GPU hosts
    the kernel would run under the Pallas interpreter, orders of
    magnitude slower — tests exercise it by calling fused_lstm_scan
    directly). ``itemsize``: activation dtype width in bytes (bounds
    the VMEM-resident weight)."""
    return (_on_tpu()
            and mask is None and gate_act == "sigmoid"
            and block_act == "tanh"
            and n % 128 == 0 and n <= _MAX_N.get(itemsize, 512)
            and _pick_block_b(b) > 0)


def train_fused_enabled() -> bool:
    """Training routes through the fused kernels (fwd + Pallas BPTT) by
    DEFAULT — measured 63.5% vs 28.8% MFU for the XLA scan-grad at the
    char-RNN bench shape (r5, BASELINE.md). DL4J_TPU_LSTM_TRAIN=xla is
    the escape hatch back to the scan."""
    import os
    return os.environ.get("DL4J_TPU_LSTM_TRAIN", "").lower() != "xla"


def fused_lstm_train_applicable(b: int, n: int, gate_act: str,
                                block_act: str, mask,
                                itemsize: int = 2) -> bool:
    """Training additionally requires the PALLAS backward to apply
    (n within the dWr-accumulator VMEM budget): falling back to the
    XLA residual BPTT from the fused forward measured SLOWER than the
    plain scan-grad (21% vs 28.8%, r3/r4), so larger hiddens keep the
    XLA scan for training. The budget scales with the stream dtype:
    bf16 admits n<=512, f32 n<=256. ``DL4J_TPU_LSTM_BWD=xla`` (the
    documented A/B seam, mirroring ``_use_pallas_bwd``) restores the
    plain XLA scan end to end — without this gate it silently
    dispatched the SLOWER fused-fwd + XLA-bwd combination."""
    import os
    if os.environ.get("DL4J_TPU_LSTM_BWD", "").lower() == "xla":
        return False
    return (train_fused_enabled() and n * itemsize <= _BWD_MAX_N * 2
            and fused_lstm_applicable(b, n, gate_act, block_act, mask,
                                      itemsize=itemsize))


def fused_lstm_scan(xg, wr, wci, wcf, wco, h0, c0
                    ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
    """xg [t, b, 4n] pre-projected gates → (h_seq [t, b, n], (h_T, c_T)).

    Differentiable end-to-end (custom VJP above); the final carries
    flow gradients too, so TBPTT chunk boundaries behave exactly like
    the XLA scan's.
    """
    t, b, g4 = xg.shape
    block_b = _pick_block_b(b)
    if block_b == 0:
        raise ValueError(
            f"batch {b} is not tileable (must be a multiple of 8); "
            f"gate with fused_lstm_applicable or use the XLA scan")
    interpret = jax.default_backend() != "tpu"
    h_seq, h_last, c_last = _fused(xg, wr, wci, wcf, wco, h0, c0,
                                   block_b, interpret)
    return h_seq, (h_last, c_last)
