"""Max pooling with an equality-mask backward (TPU-fast).

XLA differentiates ``reduce_window(max)`` through SelectAndScatter,
which is disproportionately slow on TPU: at ResNet-50's stem pool
([128, 112, 112, 64], 3x3/s2) the backward measured ~11 ms — ~22% of
the entire 49 ms train step. This custom VJP replaces it with kh*kw
dense fused passes: for each window offset, gradient flows to input
cells EQUAL to their window's max (strided slice → compare → dilate →
shifted add), all bandwidth-bound elementwise work XLA fuses well.

Tie semantics (documented deviation): SelectAndScatter routes each
window's gradient to the FIRST maximal cell; the equality mask routes
it to EVERY maximal cell. For continuous activations ties have measure
zero, and the finite-difference gradient checks (which perturb ties
away) pass identically.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def maxpool2d(x: jnp.ndarray, window: Tuple[int, int],
              strides: Tuple[int, int], pads: Tuple[int, int]) -> jnp.ndarray:
    """NHWC max pooling, symmetric spatial padding (pads = (ph, pw))."""
    kh, kw = window
    sh, sw = strides
    ph, pw = pads
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, kh, kw, 1),
                             (1, sh, sw, 1),
                             ((0, 0), (ph, ph), (pw, pw), (0, 0)))


def _fwd(x, window, strides, pads):
    y = maxpool2d(x, window, strides, pads)
    return y, (x, y)


def _bwd(window, strides, pads, res, g):
    x, y = res
    kh, kw = window
    sh, sw = strides
    ph, pw = pads
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    b, H, W, c = xp.shape
    oy, ox = y.shape[1], y.shape[2]
    g32 = g.astype(jnp.float32)
    dxp = jnp.zeros((b, H, W, c), jnp.float32)
    for ki in range(kh):
        for kj in range(kw):
            # windows whose (ki, kj) cell stays in bounds
            n_h = min(oy, (H - ki - 1) // sh + 1)
            n_w = min(ox, (W - kj - 1) // sw + 1)
            if n_h <= 0 or n_w <= 0:
                continue
            xs = lax.slice(xp, (0, ki, kj, 0),
                           (b, ki + (n_h - 1) * sh + 1,
                            kj + (n_w - 1) * sw + 1, c),
                           (1, sh, sw, 1))
            contrib = jnp.where(xs == y[:, :n_h, :n_w].astype(x.dtype),
                                g32[:, :n_h, :n_w], 0.0)
            # interior-dilate back to stride spacing, then shift into
            # place with edge padding — one fused pad+add per offset
            dil_h = (n_h - 1) * sh + 1
            dil_w = (n_w - 1) * sw + 1
            dxp = dxp + lax.pad(
                contrib, jnp.float32(0),
                ((0, 0, 0),
                 (ki, H - ki - dil_h, sh - 1),
                 (kj, W - kj - dil_w, sw - 1),
                 (0, 0, 0)))
    dx = dxp[:, ph:ph + x.shape[1], pw:pw + x.shape[2], :]
    return (dx.astype(x.dtype),)


maxpool2d.defvjp(_fwd, _bwd)
