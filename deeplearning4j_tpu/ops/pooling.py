"""Max pooling with an equality-mask backward (TPU-fast).

XLA differentiates ``reduce_window(max)`` through SelectAndScatter,
which is disproportionately slow on TPU: at ResNet-50's stem pool
([128, 112, 112, 64], 3x3/s2) the backward measured ~11 ms — ~22% of
the entire 49 ms train step. This custom VJP replaces it with kh*kw
dense fused passes: for each window offset, gradient flows to input
cells EQUAL to their window's max (strided slice → compare → dilate →
shifted add), all bandwidth-bound elementwise work XLA fuses well.

Tie semantics (documented deviation): SelectAndScatter routes each
window's gradient to the FIRST maximal cell; the equality mask splits
it EVENLY across every maximal cell (each window's contribution is
normalized by its tie count, so total gradient mass per window is
preserved — ADVICE r3: in bf16 and on post-ReLU zero plateaus exact
ties are common, so the unnormalized mask amplified gradient mass up
to kh*kw per window).

Status: OPT-IN (``DL4J_TPU_MAXPOOL_VJP=mask``). It wins the isolated
stem-pool microbenchmark ~5x but loses in-model — the ResNet-50
full-step A/B on v5e measured 49 ms/step (XLA SelectAndScatter grad)
vs 69 ms/step (this VJP); LeNet 1.64M ex/s vs 707k. The kh*kw f32
dense passes break fusion around the pool and add HBM traffic the
microbenchmark never saw. Kept for shapes where it may still win and
as the documented record of the experiment.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def maxpool2d(x: jnp.ndarray, window: Tuple[int, int],
              strides: Tuple[int, int], pads: Tuple[int, int]) -> jnp.ndarray:
    """NHWC max pooling, symmetric spatial padding (pads = (ph, pw))."""
    kh, kw = window
    sh, sw = strides
    ph, pw = pads
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, kh, kw, 1),
                             (1, sh, sw, 1),
                             ((0, 0), (ph, ph), (pw, pw), (0, 0)))


def _fwd(x, window, strides, pads):
    y = maxpool2d(x, window, strides, pads)
    return y, (x, y)


def _bwd(window, strides, pads, res, g):
    x, y = res
    kh, kw = window
    sh, sw = strides
    ph, pw = pads
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = lax.pad(x, neg, ((0, 0, 0), (ph, ph, 0), (pw, pw, 0), (0, 0, 0)))
    b, H, W, c = xp.shape
    oy, ox = y.shape[1], y.shape[2]
    ym = y.astype(x.dtype)

    # one equality mask per window offset: cell (ki,kj) of every window
    # aligned to the window's output position (every window is fully
    # in-bounds of the -inf-padded input, since oy = (H-kh)//sh + 1)
    masks = {}
    cnt = jnp.zeros(y.shape, jnp.float32)
    for ki in range(kh):
        for kj in range(kw):
            xs = lax.slice(xp, (0, ki, kj, 0),
                           (b, ki + (oy - 1) * sh + 1,
                            kj + (ox - 1) * sw + 1, c),
                           (1, sh, sw, 1))
            eq = (xs == ym).astype(jnp.float32)
            masks[ki, kj] = eq
            # per-window tie count, so gradient mass is split evenly
            # across maximal cells instead of duplicated
            cnt = cnt + eq
    g32 = g.astype(jnp.float32) / cnt

    dxp = jnp.zeros((b, H, W, c), jnp.float32)
    dil_h = (oy - 1) * sh + 1
    dil_w = (ox - 1) * sw + 1
    for (ki, kj), eq in masks.items():
        # interior-dilate back to stride spacing, then shift into
        # place with edge padding — one fused pad+add per offset
        dxp = dxp + lax.pad(
            eq * g32, jnp.float32(0),
            ((0, 0, 0),
             (ki, H - ki - dil_h, sh - 1),
             (kj, W - kj - dil_w, sw - 1),
             (0, 0, 0)))
    dx = dxp[:, ph:ph + x.shape[1], pw:pw + x.shape[2], :]
    return (dx.astype(x.dtype),)


maxpool2d.defvjp(_fwd, _bwd)
