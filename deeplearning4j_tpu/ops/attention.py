"""Scaled-dot-product / multi-head attention ops.

The reference predates attention entirely (SURVEY.md §2.6: no sequence
parallelism, no attention layers) — this module is a build-plan
extension (§7.7) that long-context support is built on. The full
(quadratic) form here is the single-device path and the correctness
oracle for the ring-attention sequence-parallel kernel in
``parallel/ring_attention.py``.

Shapes follow [batch, time, heads, head_dim] throughout.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def scaled_dot_product_attention(
    q: jnp.ndarray,  # [b, tq, h, d]
    k: jnp.ndarray,  # [b, tk, h, d]
    v: jnp.ndarray,  # [b, tk, h, d]
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,  # [b, tk] key validity
) -> jnp.ndarray:
    scale = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], q.dtype))
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    neg = jnp.asarray(jnp.finfo(scores.dtype).min, scores.dtype)
    if causal:
        tq, tk = q.shape[1], k.shape[1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        scores = jnp.where(causal_mask[None, None], scores, neg)
    if mask is not None:
        scores = jnp.where(mask[:, None, None, :] > 0, scores, neg)
    w = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def multi_head_attention(
    x: jnp.ndarray,  # [b, t, f]
    wq: jnp.ndarray, wk: jnp.ndarray, wv: jnp.ndarray,  # [f, h*d]
    wo: jnp.ndarray,  # [h*d, f]
    num_heads: int,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    b, t, f = x.shape
    d = wq.shape[-1] // num_heads
    split = lambda z: z.reshape(b, t, num_heads, d)
    q, k, v = split(x @ wq), split(x @ wk), split(x @ wv)
    o = scaled_dot_product_attention(q, k, v, causal=causal, mask=mask)
    return o.reshape(b, t, num_heads * d) @ wo
