"""Mixture-of-experts FFN with top-1 (Switch-style) routing.

No reference counterpart (SURVEY §2.6 note 5: the reference predates
expert parallelism); build-plan extension. TPU-first formulation: hard
routing is expressed as dense dispatch/combine one-hot tensors and
einsums — gathers/scatters become MXU matmuls, shapes stay static
(capacity-bounded), and when the expert dimension of the weights is
sharded over a mesh ``expert`` axis XLA lowers the dispatched einsum to
the canonical all-to-all. Overflowed tokens (expert over capacity) pass
through the residual path with zero expert output, as in Switch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def top1_dispatch(gate_logits: jnp.ndarray, capacity: int,
                  valid: jnp.ndarray = None):
    """gate_logits [n, E] → (dispatch [n, E, C] one-hot, combine
    [n, E, C] gate-weighted, aux_loss scalar).

    ``valid`` [n] (optional): masked-out tokens are routed nowhere —
    they consume no capacity slots and are excluded from the aux loss
    (padded timesteps must not starve real tokens of capacity).

    aux_loss is the Switch load-balancing loss E·Σ_e f_e·p_e (fraction
    routed × mean router prob) — add it to the training objective to
    keep experts utilized.
    """
    n, e = gate_logits.shape
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                      # [n]
    onehot = jax.nn.one_hot(expert, e, dtype=jnp.float32)    # [n, E]
    if valid is not None:
        v = valid.astype(jnp.float32)
        onehot = onehot * v[:, None]
        n_valid = jnp.maximum(jnp.sum(v), 1.0)
    else:
        v = None
        n_valid = float(n)
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0          # [n, E]
    keep = (pos >= 0) & (pos < capacity)
    pos_clamped = jnp.clip(pos, 0, capacity - 1).astype(jnp.int32)
    dispatch = (jax.nn.one_hot(pos_clamped, capacity, dtype=jnp.float32)
                * keep[..., None].astype(jnp.float32))       # [n, E, C]
    gate = jnp.sum(probs * onehot, axis=-1)                  # [n]
    combine = dispatch * gate[:, None, None]
    frac_routed = jnp.sum(onehot, axis=0) / n_valid
    mean_prob = (jnp.sum(probs * (v[:, None] if v is not None else 1.0),
                         axis=0) / n_valid)
    aux_loss = e * jnp.sum(frac_routed * mean_prob)
    return dispatch, combine, aux_loss


def moe_ffn(x: jnp.ndarray, Wg, W1, b1, W2, b2,
            capacity_factor: float = 1.25, valid: jnp.ndarray = None):
    """x [n, d] → ([n, d], aux_loss). Expert weights: W1 [E, d, f],
    b1 [E, f], W2 [E, f, d], b2 [E, d]; router Wg [d, E]. ``valid``
    [n]: tokens to route (masked tokens get zero output and no slot)."""
    n, d = x.shape
    e = W1.shape[0]
    capacity = max(1, int(capacity_factor * n / e))
    gate_logits = x.astype(jnp.float32) @ Wg.astype(jnp.float32)
    dispatch, combine, aux = top1_dispatch(gate_logits, capacity, valid=valid)
    dispatch = dispatch.astype(x.dtype)
    combine = combine.astype(x.dtype)
    xe = jnp.einsum("nec,nd->ecd", dispatch, x)              # [E, C, d]
    h = jax.nn.gelu(jnp.einsum("ecd,edf->ecf", xe, W1) + b1[:, None, :])
    ye = jnp.einsum("ecf,efd->ecd", h, W2) + b2[:, None, :]  # [E, C, d]
    y = jnp.einsum("ecd,nec->nd", ye, combine)
    return y, aux
