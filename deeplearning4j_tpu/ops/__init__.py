"""Functional op layer — the role ND4J/libnd4j plays for the reference.

Everything here is a pure jax-traceable function. Where DL4J routed each
call through ``INDArray``/``Nd4j.getExecutioner()`` (one native kernel per
op), here ops are composed in Python and fused by XLA into the enclosing
jitted step, which is the TPU-correct design: elementwise work fuses into
the surrounding matmuls/convs instead of round-tripping HBM.
"""

from deeplearning4j_tpu.ops.activations import Activation, activate  # noqa: F401
from deeplearning4j_tpu.ops.losses import LossFunction, compute_loss  # noqa: F401
from deeplearning4j_tpu.ops.flash_attention import flash_attention  # noqa: F401
