"""Activation functions.

Parity with the reference's activation set (DL4J 0.6.1 string-keyed
activations applied via ND4J transform ops, see
``nn/layers/BaseLayer.java`` ``activate``/``preOutput`` and
``org.nd4j.linalg.api.ops.impl.transforms``). TPU note: these are plain
jax functions so XLA fuses them into the preceding matmul/conv — the
reference paid one kernel launch + HBM round-trip per activation.
"""

from __future__ import annotations

import enum
from typing import Callable, Union

import jax
import jax.numpy as jnp


class Activation(str, enum.Enum):
    """String-keyed activation registry (reference: DL4J ``activation("relu")``)."""

    IDENTITY = "identity"
    SIGMOID = "sigmoid"
    TANH = "tanh"
    RELU = "relu"
    LEAKYRELU = "leakyrelu"
    SOFTMAX = "softmax"
    SOFTPLUS = "softplus"
    SOFTSIGN = "softsign"
    ELU = "elu"
    HARDTANH = "hardtanh"
    HARDSIGMOID = "hardsigmoid"
    CUBE = "cube"
    RATIONALTANH = "rationaltanh"
    RRELU = "rrelu"  # treated as leakyrelu at inference; randomized slope in train
    GELU = "gelu"  # extension beyond the reference (modern models need it)
    SILU = "silu"  # extension (swish)


def _rationaltanh(x: jnp.ndarray) -> jnp.ndarray:
    # Rational approximation of tanh used by DL4J (ND4J RationalTanh op):
    # f(x) = 1.7159 * tanh_approx(2x/3) with tanh_approx(y) =
    #        sign(y) * (1 - 1/(1 + |y| + y^2 + 1.41645 y^4))
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = jnp.sign(y) * (1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y**4)))
    return 1.7159 * approx


_FUNCS: dict[Activation, Callable[[jnp.ndarray], jnp.ndarray]] = {
    Activation.IDENTITY: lambda x: x,
    Activation.SIGMOID: jax.nn.sigmoid,
    Activation.TANH: jnp.tanh,
    Activation.RELU: jax.nn.relu,
    Activation.LEAKYRELU: lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    Activation.SOFTPLUS: jax.nn.softplus,
    Activation.SOFTSIGN: jax.nn.soft_sign,
    Activation.ELU: jax.nn.elu,
    Activation.HARDTANH: lambda x: jnp.clip(x, -1.0, 1.0),
    Activation.HARDSIGMOID: jax.nn.hard_sigmoid,
    Activation.CUBE: lambda x: x**3,
    Activation.RATIONALTANH: _rationaltanh,
    Activation.RRELU: lambda x: jax.nn.leaky_relu(x, negative_slope=0.01),
    Activation.GELU: lambda x: jax.nn.gelu(x, approximate=False),
    Activation.SILU: jax.nn.silu,
}


def activate(name: Union[str, Activation], x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Apply activation ``name`` to ``x``.

    ``softmax`` normalizes over ``axis`` (default last = feature dim; the
    reference's 2d [batch, nOut] softmax along dim 1).
    """
    act = Activation(name)
    if act is Activation.SOFTMAX:
        return jax.nn.softmax(x, axis=axis)
    return _FUNCS[act](x)


def activation_gradient(name: Union[str, Activation], x: jnp.ndarray) -> jnp.ndarray:
    """Elementwise derivative d act(x) / dx (softmax excluded — its backprop
    is handled jointly with the loss, as in the reference output layer).

    Exists for parity tests against hand-math (BackPropMLPTest-style);
    production backprop is ``jax.grad`` through :func:`activate`.
    """
    act = Activation(name)
    if act is Activation.SOFTMAX:
        raise ValueError("softmax gradient is handled jointly with the loss")
    grad = jax.vmap(jax.grad(lambda v: _FUNCS[act](v)))
    return grad(x.reshape(-1)).reshape(x.shape)
